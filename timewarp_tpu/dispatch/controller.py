"""The online adaptive dispatch controller (ROADMAP "Online adaptive
dispatch"; TempoNet's slack-quantized deadline-centric framing,
PAPERS.md).

A :class:`DispatchController` sits **between** jitted chunks of the
chunked drivers (``run_controlled`` — interp/jax_engine/controlled.py;
the sweep service's BucketRunner drives the same contract per bucket)
and adapts three dispatch knobs online from the telemetry the previous
chunk streamed (``engine.last_run_telemetry``, obs/):

- **window width** — widen toward the engine's window *bound* (the
  undegraded link floor) when supersteps run sparse, narrow when the
  fault schedule's per-window link floor says a degradation window
  overlaps the upcoming virtual-time span
  (``FaultSchedule.min_delay_floor_in``; the device-side clamp
  ``faults.apply.window_floor`` independently guarantees exactness,
  so the host query is *policy*, never a correctness dependence);
- **rung pinning** — a floor on the adaptive routing ladder's selected
  index when the observed rung column thrashes (the effective index
  is ``max(computed, pin)``: a pin can only widen, so it is
  result-identical by the ladder's own construction);
- **chunk length** — a pow2 ladder between ``chunk_min`` and
  ``chunk_max``, shrunk when worlds quiesce mid-chunk (budget-mask
  waste — the ``bucket_util`` signal) and grown when every superstep
  of the chunk ran.

Nothing here touches a traced value: knobs reach the executable as
ordinary traced scalars (``DynDispatch``), so **no adaptation ever
retraces** — the pow2 scan pad stays the drivers' only static compile
input, and every adapted configuration resolves through the already-
compiled executable cache (the zero-recompile acceptance,
tests/test_zzzdispatch.py).

Every decision is recorded (dispatch/trace.py) and the controller
accepts a prior trace: ``mode="replay"`` re-applies a full recorded
run (the **replay law** — bit-identical states/traces/digests/
checkpoints), while ``mode="auto"`` with ``replay=`` re-applies a
journaled *prefix* before deciding fresh — exactly what ``sweep
resume`` needs so decisions journaled before a kill are never re-made
differently (sweep/runner.py).
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from .trace import Decision, DecisionTrace, DispatchTraceError

__all__ = ["DispatchController", "parse_controller",
           "CONTROLLER_GRAMMAR"]

#: the --controller grammar, named in every parse error
CONTROLLER_GRAMMAR = ("auto | off | replay:<trace.jsonl>  "
                      "(auto adapts from telemetry and records a "
                      "decision trace; replay re-applies a recorded "
                      "trace bit-for-bit)")


def parse_controller(spec: Optional[str]):
    """The CLI constructor: ``auto`` | ``off``/None | ``replay:PATH``.
    Malformed specs die naming :data:`CONTROLLER_GRAMMAR`."""
    if spec is None or spec == "off":
        return None
    if spec == "auto":
        return DispatchController()
    if spec.startswith("replay:"):
        path = spec[len("replay:"):]
        if not path:
            raise SystemExit(
                f"replay needs a trace path; grammar: "
                f"{CONTROLLER_GRAMMAR}")
        try:
            return DispatchController(
                mode="replay", replay=DecisionTrace.load(path))
        except DispatchTraceError as e:
            raise SystemExit(str(e)) from None
    raise SystemExit(f"unknown --controller spec {spec!r}; grammar: "
                     f"{CONTROLLER_GRAMMAR}")


def _pow2_at_most(x: int) -> int:
    return 1 << (max(int(x), 1).bit_length() - 1)


class DispatchController:
    """Module docstring. One controller drives one run at a time
    (:meth:`begin` rebinds it to an engine); decisions accumulate in
    :attr:`made` keyed by chunk index, so a sweep retry that re-runs a
    chunk REUSES its decision instead of re-deriving it from telemetry
    the crash destroyed."""

    MODES = ("auto", "replay")

    def __init__(self, mode: str = "auto", *, replay=None,
                 chunk: int = 32, chunk_min: int = 8,
                 chunk_max: int = 256,
                 density_lo: int = 2) -> None:
        if mode not in self.MODES:
            raise ValueError(
                f"controller mode must be one of {self.MODES}, got "
                f"{mode!r} (the 'off' state is no controller at all)")
        for name, v in (("chunk", chunk), ("chunk_min", chunk_min),
                        ("chunk_max", chunk_max)):
            if v < 1:
                raise ValueError(f"{name} must be >= 1, got {v}")
        if chunk_min > chunk_max:
            raise ValueError(
                f"chunk_min={chunk_min} > chunk_max={chunk_max}")
        self.mode = mode
        self.chunk_init = _pow2_at_most(chunk)
        self.chunk_min = _pow2_at_most(chunk_min)
        self.chunk_max = _pow2_at_most(chunk_max)
        #: mean active senders per superstep below which a chunk is
        #: "sparse" and the window widens toward the bound
        self.density_lo = int(density_lo)
        #: every decision governing this run, keyed by chunk index —
        #: the replay prefix lands here up front, fresh auto decisions
        #: join as they are made
        self.made: Dict[int, Decision] = {}
        self._replay_len = 0
        if replay is not None:
            for d in (replay.decisions if isinstance(replay,
                                                     DecisionTrace)
                      else replay):
                if isinstance(d, dict):
                    d = Decision.from_json(d, where="replay record")
                if d.chunk in self.made \
                        and not self.made[d.chunk].same_knobs(d):
                    raise DispatchTraceError(
                        f"replay holds two DIFFERENT decisions for "
                        f"chunk {d.chunk} — refusing to pick one")
                self.made[d.chunk] = d
            self._replay_len = (max(self.made) + 1) if self.made else 0
        elif mode == "replay":
            raise ValueError(
                "mode='replay' needs replay= (a DecisionTrace, a "
                "decision list, or journal records)")
        # engine binding (begin)
        self._bound: Optional[int] = None
        self._dyn_ok = False
        self._rungs: Optional[List[int]] = None
        self._sched = None
        self._batched = False
        self._mb_cap = 0

    # -- binding -----------------------------------------------------------

    def begin(self, engine) -> None:
        """Bind to an engine for one run: capture the window bound,
        the rung ladder (when one will actually run), and the fault
        schedule for per-window floor queries — and validate every
        replay/prefix decision against those bounds, so a trace
        recorded for a different configuration fails HERE, loudly,
        not as a silent clamp mid-run."""
        self._dyn_ok = bool(getattr(engine, "_dyn_ok", False))
        self._bound = int(getattr(engine, "window", 1))
        self._sched = getattr(engine, "faults", None)
        self._batched = getattr(engine, "batch", None) is not None
        self._mb_cap = int(getattr(engine.scenario, "mailbox_cap", 0))
        self._rungs = None
        if self._dyn_ok and not self._batched:
            regime = getattr(engine, "_adaptive_regime", None)
            if regime is not None and regime():
                rungs = engine._sender_rungs(engine.scenario.n_nodes)
                if len(rungs) > 1:
                    self._rungs = list(rungs)
        top_pin = -1 if self._rungs is None else len(self._rungs) - 1
        for d in self.made.values():
            if d.window_us > self._bound:
                raise DispatchTraceError(
                    f"replayed decision for chunk {d.chunk} requests "
                    f"window {d.window_us} µs beyond this engine's "
                    f"bound {self._bound} µs — the trace was recorded "
                    "for a different configuration")
            if d.rung_pin > top_pin:
                raise DispatchTraceError(
                    f"replayed decision for chunk {d.chunk} pins rung "
                    f"index {d.rung_pin} but this engine's ladder has "
                    f"{top_pin + 1} pinnable rungs")

    @property
    def decisions(self) -> List[Decision]:
        """Every decision made/replayed so far, in chunk order."""
        return [self.made[i] for i in sorted(self.made)]

    def trace(self) -> DecisionTrace:
        return DecisionTrace.of(self.decisions)

    # -- the per-chunk decision point -------------------------------------

    def decide(self, chunk_index: int, frames, t_now: int
               ) -> Tuple[Decision, bool]:
        """The decision for chunk ``chunk_index``. Returns
        ``(decision, fresh)`` — ``fresh=False`` means it was replayed
        (from a prior trace, a journaled prefix, or an earlier attempt
        of the same chunk) and must NOT be re-journaled. ``frames`` is
        the previous chunk's decoded telemetry
        (``engine.last_run_telemetry``: a TelemetryFrames, a per-world
        list, or None before the first chunk / after a retry reload);
        ``t_now`` the fleet's current virtual time."""
        if chunk_index in self.made:
            return self.made[chunk_index], False
        if self.mode == "replay":
            raise DispatchTraceError(
                f"replay trace exhausted at chunk {chunk_index} "
                f"(holds {self._replay_len}): the replayed run needed "
                "more chunks than the recorded one — the engine "
                "configuration does not match the trace")
        dec = self._auto(chunk_index, frames, int(t_now))
        self.made[chunk_index] = dec
        return dec, True

    # -- the auto policy ---------------------------------------------------

    def _signals(self, frames) -> Optional[dict]:
        """Fold one chunk's telemetry into the scalar signals the
        policy reads. Batched fleets reduce per-world columns with the
        RECORDED aggregations: quiescence slack by ``min`` over worlds
        (a fleet window/chunk must suit the tightest world), load by
        ``max``, density by ``mean`` — the reductions land in the
        decision's ``obs`` so a trace reader can audit them."""
        if frames is None:
            return None
        flist = frames if isinstance(frames, list) else [frames]
        if all(len(f) == 0 for f in flist):
            return None
        sup = max(len(f) for f in flist)
        act = np.concatenate([f.data["active_senders"] for f in flist
                              if len(f)])
        rungs = np.concatenate([f.data["rung"] for f in flist
                                if len(f)])
        slack = np.concatenate([f.data["qslack_us"] for f in flist
                                if len(f)])
        live_slack = slack[slack >= 0]
        sig = {
            "supersteps": int(sup),
            "active_mean": float(act.mean()),
            "active_max": int(act.max()),
            "rung_used": sorted(int(r) for r in set(rungs.tolist())
                                if r >= 0),
            "qslack_min": int(live_slack.min()) if live_slack.size
            else -1,
            "span_us": int(max(int(f.t_us[-1]) - int(f.t_us[0])
                               for f in flist if len(f))),
            "agg": "slack:min-over-worlds,load:max-over-worlds"
            if len(flist) > 1 else "solo",
        }
        if any("mb_peak" in f.data and len(f) for f in flist):
            sig["mb_peak"] = int(max(
                int(f.data["mb_peak"].max()) for f in flist
                if "mb_peak" in f.data and len(f)))
        return sig

    def _auto(self, ci: int, frames, t_now: int) -> Decision:
        prev = self.made.get(ci - 1)
        sig = self._signals(frames)
        chunk = prev.chunk_len if prev is not None else self.chunk_init
        chunk = min(max(chunk, self.chunk_min), self.chunk_max)
        obs: Dict[str, Any] = {"t_now": t_now}
        # -- window: start wide (the bound — exactness never depends
        # on the request: the per-superstep device clamp
        # faults/apply.window_floor is the narrowing authority, at
        # finer granularity than any per-chunk request could be),
        # halve under observed mailbox pressure (the overflow-boundary
        # caveat is what makes a narrower window ever preferable),
        # re-widen when pressure clears. The fault tables' per-window
        # link floor over the upcoming span is consumed and RECORDED
        # (obs.floor_h_us) so a trace reader sees the degradation
        # narrowing the controller expects the clamp to apply.
        w = prev.window_us if prev is not None else self._bound
        if self._dyn_ok:
            if sig is not None:
                mbp = sig.get("mb_peak")
                if mbp is not None and self._mb_cap \
                        and 10 * mbp >= 9 * self._mb_cap:
                    w = max(1, w // 2)
                elif w < self._bound:
                    w = min(self._bound, max(1, w) * 2)
            else:
                w = self._bound
            if self._sched is not None \
                    and hasattr(self._sched, "min_delay_floor_in"):
                span = sig["span_us"] if sig is not None \
                    else self._bound * chunk
                horizon = max(int(span), self._bound)
                obs["floor_h_us"] = int(
                    self._sched.min_delay_floor_in(
                        self._bound, t_now, t_now + horizon))
                obs["horizon_us"] = horizon
        else:
            # window is a static compile parameter on this engine
            # (fused kernels bake it; the edge engine runs classic
            # supersteps) — recorded as the pinned value
            w = max(1, self._bound)
            obs["window"] = "static"
        if sig is not None:
            obs.update({k: (round(v, 3) if isinstance(v, float) else v)
                        for k, v in sig.items()})
            # -- chunk length: shrink when the chunk ran mostly masked
            # tail (worlds quiesced / budgets exhausted mid-chunk),
            # grow when every superstep ran
            if prev is not None:
                full = sig["supersteps"] / max(prev.chunk_len, 1)
                obs["full_frac"] = round(full, 3)
                if full <= 0.5:
                    chunk = max(self.chunk_min,
                                _pow2_at_most(max(sig["supersteps"],
                                                  1)))
                elif full >= 1.0:
                    chunk = min(self.chunk_max, chunk * 2)
            # -- rung pin: the ladder thrashed across rungs within one
            # chunk -> floor it at the widest rung the chunk needed
            # (result-identical: max(computed, pin) can only widen)
            if self._rungs is not None and len(sig["rung_used"]) > 1:
                widest = max(sig["rung_used"])
                pin = self._rungs.index(widest) \
                    if widest in self._rungs else -1
            else:
                pin = -1
        else:
            pin = -1
        if self._rungs is None:
            pin = -1
        return Decision(chunk=ci, window_us=int(w), rung_pin=int(pin),
                        chunk_len=int(chunk), obs=obs)
