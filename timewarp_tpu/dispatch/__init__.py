"""Online adaptive dispatch (docs/dispatch.md): a host-side controller
that auto-tunes the engines' dispatch knobs — superstep window width,
adaptive-routing rung pinning, scan chunk length — between jitted
chunks, from the telemetry the previous chunk streamed (obs/), with
**no retrace in the hot loop** and a recorded decision trace whose
replay is bit-identical (the replay law)."""

from .controller import (CONTROLLER_GRAMMAR, DispatchController,
                         parse_controller)
from .trace import (DISPATCH_SCHEMA, Decision, DecisionTrace,
                    DispatchTraceError)

__all__ = [
    "CONTROLLER_GRAMMAR", "DISPATCH_SCHEMA", "Decision",
    "DecisionTrace", "DispatchController", "DispatchTraceError",
    "parse_controller",
]
