"""The dispatch decision trace: schema'd, journalable, replayable.

One :class:`Decision` per executed chunk of a controller-driven run
(dispatch/controller.py): the three knob values the chunk ran with —
requested window width, routing-ladder rung pin, chunk length — plus
an ``obs`` dict recording the telemetry the decision was derived from
(including, for batched fleets, the *reduction* used to aggregate
per-world signals into one fleet decision). The trace IS the run's
dispatch identity: re-executing the same engine configuration while
replaying the trace is bit-identical on states, traces, digests, and
checkpoints — the **replay law** (docs/dispatch.md;
tests/test_zzzdispatch.py pins it solo, batched, and under faults).

Serialized form is JSONL, one record per line::

    {"schema": 1, "kind": "decision", "chunk": 0, "window_us": 8000,
     "rung_pin": -1, "chunk_len": 32, "obs": {...}}

the same record shape the sweep journal embeds as
``dispatch_decision`` events (sweep/journal.py) and the metrics
registry validates as the ``decision`` kind (obs/metrics.py) — one
schema, three sinks.
"""

from __future__ import annotations

import json
from dataclasses import dataclass, field
from typing import Any, Dict, List, Tuple

__all__ = ["DISPATCH_SCHEMA", "Decision", "DecisionTrace",
           "DispatchTraceError"]

#: bump when the decision record's required fields change shape
DISPATCH_SCHEMA = 1


class DispatchTraceError(ValueError):
    """A decision trace is malformed or contradicts the run it is
    replayed against — never silently reconciled."""


@dataclass(frozen=True)
class Decision:
    """One chunk's knob values (module docstring). ``obs`` is
    observability metadata — replay applies only the knobs, so two
    decisions with equal knobs and different obs replay identically
    (equality for the replay-consistency checks therefore compares
    knobs only via :meth:`same_knobs`)."""
    chunk: int          # 0-based chunk index within the run
    window_us: int      # requested superstep window width
    rung_pin: int       # ladder index floor (-1 = unpinned)
    chunk_len: int      # supersteps this chunk may run per world
    obs: Dict[str, Any] = field(default_factory=dict)

    def __post_init__(self):
        for name in ("chunk", "window_us", "rung_pin", "chunk_len"):
            v = getattr(self, name)
            if isinstance(v, bool) or not isinstance(v, int):
                raise DispatchTraceError(
                    f"decision field {name!r} must be an int, "
                    f"got {v!r}")
        if self.chunk < 0:
            raise DispatchTraceError(
                f"decision chunk index must be >= 0, got {self.chunk}")
        if self.window_us < 1:
            raise DispatchTraceError(
                f"decision window_us must be >= 1, got {self.window_us}")
        if self.rung_pin < -1:
            raise DispatchTraceError(
                f"decision rung_pin must be >= -1, got {self.rung_pin}")
        if self.chunk_len < 1:
            raise DispatchTraceError(
                f"decision chunk_len must be >= 1, got {self.chunk_len}")

    def same_knobs(self, other: "Decision") -> bool:
        """Replay-relevant equality: the knob values (obs is free)."""
        return (self.chunk == other.chunk
                and self.window_us == other.window_us
                and self.rung_pin == other.rung_pin
                and self.chunk_len == other.chunk_len)

    def to_json(self) -> Dict[str, Any]:
        return {"schema": DISPATCH_SCHEMA, "kind": "decision",
                "chunk": self.chunk, "window_us": self.window_us,
                "rung_pin": self.rung_pin, "chunk_len": self.chunk_len,
                "obs": dict(self.obs)}

    @classmethod
    def from_json(cls, d: Any, where: str = "decision") -> "Decision":
        if not isinstance(d, dict):
            raise DispatchTraceError(
                f"{where}: a decision record is a JSON object, "
                f"got {type(d).__name__}")
        if d.get("schema") != DISPATCH_SCHEMA:
            raise DispatchTraceError(
                f"{where}: decision schema {d.get('schema')!r} != "
                f"{DISPATCH_SCHEMA} (this reader)")
        if d.get("kind") != "decision":
            raise DispatchTraceError(
                f"{where}: kind {d.get('kind')!r} != 'decision'")
        try:
            return cls(chunk=d["chunk"], window_us=d["window_us"],
                       rung_pin=d["rung_pin"], chunk_len=d["chunk_len"],
                       obs=dict(d.get("obs") or {}))
        except KeyError as e:
            raise DispatchTraceError(
                f"{where}: decision record is missing field {e}"
            ) from None


@dataclass(frozen=True)
class DecisionTrace:
    """An ordered, gapless run of decisions (chunk 0, 1, 2, …) — what
    ``--decisions-out`` writes and ``--controller replay:<trace>``
    loads. Construction validates the indexing, so a truncated or
    shuffled file fails at load, not mid-run."""
    decisions: Tuple[Decision, ...]

    def __post_init__(self):
        for i, d in enumerate(self.decisions):
            if d.chunk != i:
                raise DispatchTraceError(
                    f"decision trace is not gapless: position {i} "
                    f"holds chunk {d.chunk} (a trace is the full "
                    "ordered decision sequence of one run)")

    def __len__(self) -> int:
        return len(self.decisions)

    def __getitem__(self, i: int) -> Decision:
        return self.decisions[i]

    def save(self, path: str) -> str:
        with open(path, "w") as f:
            for d in self.decisions:
                f.write(json.dumps(d.to_json(), sort_keys=True) + "\n")
        return path

    @classmethod
    def load(cls, path: str) -> "DecisionTrace":
        decs: List[Decision] = []
        try:
            with open(path) as f:
                lines = f.read().splitlines()
        except OSError as e:
            raise DispatchTraceError(
                f"cannot read decision trace {path!r}: {e}") from None
        for i, line in enumerate(lines, 1):
            if not line.strip():
                continue
            try:
                rec = json.loads(line)
            except json.JSONDecodeError as e:
                raise DispatchTraceError(
                    f"{path}:{i}: not JSON ({e})") from None
            decs.append(Decision.from_json(rec, where=f"{path}:{i}"))
        if not decs:
            raise DispatchTraceError(
                f"decision trace {path!r} holds no decisions")
        return cls(tuple(decs))

    @classmethod
    def of(cls, decisions) -> "DecisionTrace":
        return cls(tuple(decisions))
