"""timewarp_tpu — a TPU-native framework for writing distributed-system
scenarios once and running them under interchangeable interpreters.

Capability parity target: `input-output-hk/time-warp` (see SURVEY.md).
The three interpreters:

- :mod:`timewarp_tpu.interp.ref` — pure deterministic discrete-event
  emulation on the host (the oracle; ≙ ``TimedT``).
- :mod:`timewarp_tpu.interp.jax_engine` — the batched XLA engine:
  per-node step functions ``vmap``-ed over the node axis, virtual time
  driven by ``lax.scan``, message delivery as sharded collectives over
  the TPU mesh. This is what the reference never had: emulation that
  *scales*.
- :mod:`timewarp_tpu.interp.aio` — real wall-clock mode over asyncio
  TCP (≙ ``TimedIO`` + ``Transfer``).

All interpreters agree on observable event traces (bit-for-bit at small
node counts — the framework's core law, tested in tests/test_parity*).
"""

from .core import effects, errors, time
from .core.effects import (Fork, ForkSlave, GetLogName, GetTime, MyTid,
                           SetLogName, ThrowTo, Wait, fork, fork_,
                           fork_slave, invoke, kill_thread, modify_log_name,
                           my_thread_id, repeat_forever, schedule,
                           sleep_forever, start_timer, timeout,
                           virtual_time, wait, work)
from .core.errors import (AlreadyListening, MailboxOverflow, NetworkError,
                          PeerClosedConnection, ThreadKilled, TimedError,
                          TimeoutExpired, TimeWarpError, TransferError)
from .core.time import (FOREVER, Microsecond, after, at, for_, hour, mcs,
                        minute, ms, now, sec, till)
from .interp.aio.timed import AioThreadId, RealTime, run_real_time
from .interp.ref.des import PureEmulation, PureThreadId, run_emulation
from .manage.jobs import Force, InterruptType, JobCurator, Plain, WithTimeout
from .manage.sync import CLOSED, Channel, Flag, MVar

__version__ = "0.1.0"
