"""Deterministic state corruption: the ``flip:`` chaos grammar.

The PR 5 sweep-machinery chaos grammar (``--inject fail:K | oom:K |
die:K | hang:K:MS``, sweep/service.py) grows a fourth form::

    flip:SEED[:CHUNK[:PLANE]]

— a **seeded bit-flip written into a state plane between chunks**,
the test/CI lever the detection law is pinned against
(tests/test_zzzzintegrity.py): every injected flip must be detected
within the configured verify cadence, and the rolled-back run must be
bit-identical to an uninjected run. ``SEED`` keys the element and bit
choice, ``CHUNK`` (1-based, default 1) picks the chunk boundary the
flip lands on, ``PLANE`` names a state field (``mb_rel``, ``wake``,
``delivered``, ``states.<leaf>``, …; default seed-chosen among the
non-empty planes).

The flip is applied host-side between chunks — exactly the window the
``digest`` verify mode's entry check covers — and each spec fires
once (rollback re-runs the same chunk index; the injector must not
re-corrupt the recovered state, or no recovery could ever converge).

Malformed specs die naming :data:`INJECT_GRAMMAR`, never a raw
traceback (tests/test_zgrammar.py) — the same loud-grammar contract
as LINK_GRAMMAR / FAULT_GRAMMAR.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Optional, Tuple

import numpy as np

__all__ = ["INJECT_GRAMMAR", "FlipSpec", "parse_flip", "apply_flip",
           "FlipInjector"]

#: the flip form of the sweep --inject grammar (sweep/service.py
#: InjectPlan carries the full four-form grammar string)
INJECT_GRAMMAR = ("flip:SEED[:CHUNK[:PLANE]]  (seeded bit-flip "
                  "written into a state plane before chunk CHUNK "
                  "(1-based, default 1); PLANE = a state field name, "
                  "default seed-chosen)")


@dataclass(frozen=True)
class FlipSpec:
    seed: int
    chunk: int = 1
    plane: Optional[str] = None


def parse_flip(part: str) -> FlipSpec:
    """Parse one ``flip:...`` spec; raises ``ValueError`` naming
    INJECT_GRAMMAR on any malformation (the sweep's InjectPlan
    re-raises it as a SweepConfigError; an embedding caller gets a
    catchable error either way)."""
    bits = part.split(":")
    try:
        if bits[0] != "flip" or not 2 <= len(bits) <= 4:
            raise ValueError(part)
        seed = int(bits[1])
        chunk = int(bits[2]) if len(bits) >= 3 else 1
        plane = bits[3] if len(bits) == 4 else None
        if seed < 0 or chunk < 1 or (plane is not None and not plane):
            raise ValueError(part)
        return FlipSpec(seed=seed, chunk=chunk, plane=plane)
    except (IndexError, ValueError):
        raise ValueError(
            f"malformed flip spec {part!r}; grammar: "
            f"{INJECT_GRAMMAR}") from None


def _leaf_names(state) -> Tuple[list, list, object]:
    """Flatten a state pytree with dotted path names (``mb_rel``,
    ``states.cnt``, …) — what ``PLANE`` matches against."""
    import jax
    path_leaves, treedef = jax.tree_util.tree_flatten_with_path(state)

    def name(path) -> str:
        parts = []
        for k in path:
            if hasattr(k, "name"):
                parts.append(str(k.name))
            elif hasattr(k, "key"):
                parts.append(str(k.key))
            elif hasattr(k, "idx"):
                parts.append(str(k.idx))
            else:
                parts.append(str(k))
        return ".".join(parts)
    names = [name(p) for p, _ in path_leaves]
    leaves = [x for _, x in path_leaves]
    return names, leaves, treedef


def apply_flip(state, seed: int, plane: Optional[str] = None):
    """Flip one seeded bit (or invert one seeded bool) in one leaf of
    ``state``; returns ``(corrupted_state, description)``. Pure: the
    input pytree is untouched (arrays are copied before the flip), so
    a caller's snapshot of the clean state stays clean — which is
    exactly what makes rollback recovery testable."""
    import jax
    names, leaves, treedef = _leaf_names(state)
    rng = np.random.default_rng(seed)
    eligible = [i for i, x in enumerate(leaves) if np.asarray(
        jax.device_get(x)).size > 0]
    if not eligible:
        raise ValueError("state has no non-empty plane to flip")
    if plane is not None:
        cand = [i for i in eligible
                if names[i] == plane or names[i].endswith("." + plane)]
        if not cand:
            raise ValueError(
                f"flip plane {plane!r} names no non-empty state "
                f"field; available: {[names[i] for i in eligible]}")
        li = cand[0]
    else:
        li = eligible[int(rng.integers(len(eligible)))]
    arr = np.array(jax.device_get(leaves[li]))  # a copy — pure
    flat = arr.reshape(-1)
    ei = int(rng.integers(flat.size))
    if arr.dtype == bool:
        flat[ei] = not flat[ei]
        desc = f"{names[li]}[{ei}] bool inverted (seed {seed})"
    else:
        view = flat[ei:ei + 1].view(np.uint8)
        bit = int(rng.integers(view.size * 8))
        view[bit // 8] ^= np.uint8(1 << (bit % 8))
        desc = f"{names[li]}[{ei}] bit {bit} flipped (seed {seed})"
    leaves = list(leaves)
    leaves[li] = arr
    return jax.tree.unflatten(treedef, leaves), desc


class FlipInjector:
    """The engine-level corruption hook ``run_verified(inject=...)``
    takes (runner.py): fires its flip ONCE, at its chunk boundary,
    and records what it did (``fired`` / ``desc``) so tests and the
    in-bench detection gate can assert the flip actually happened."""

    def __init__(self, spec) -> None:
        self.spec = parse_flip(spec) if isinstance(spec, str) else spec
        self.fired = False
        self.desc: Optional[str] = None

    def __call__(self, chunk_idx: int, state):
        if self.fired or chunk_idx != self.spec.chunk - 1:
            return None
        self.fired = True
        new, self.desc = apply_flip(state, self.spec.seed,
                                    self.spec.plane)
        return new
