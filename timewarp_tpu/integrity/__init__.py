"""Self-verifying execution: online state-integrity checking with
deterministic rollback recovery (docs/integrity.md).

The repo's exactness laws (engine ≡ oracle, strategy ≡ strategy,
world-slice ≡ solo, controller ≡ replay) are *design-time* guarantees;
this package is what uses them at **run time**. Every scan-driver
engine grows a ``verify=`` knob — ``"off" | "guard" | "digest" |
"shadow"``, an escalating ladder with the telemetry subsystem's
zero-overhead-when-off contract (the off-mode jaxpr is byte-identical
to the pre-knob engine):

- ``"guard"`` — fixed-shape on-device invariant checks threaded
  through the traced scan (checks.py): virtual time monotone
  non-decreasing, no negative never-silent counter, wake/mailbox
  entries never in the past (unfaulted runs), the ``restart_done``
  ledger monotone against the fault tables. The first violating
  superstep + field surfaces in the pinned TraceMismatch-style
  diagnostic format (:class:`IntegrityViolation`).
- ``"digest"`` — guard, plus a cheap fixed-shape rolling digest of the
  whole engine state per chunk on-device (digest.py), recomputed at
  every chunk *entry*: a bit flipped in HBM (or a checkpoint restored
  corrupt) between chunks changes the digest and is detected within
  the configured cadence. The digest chains through
  ``last_run_stats`` / the metrics stream, and extends the sweep
  checkpoints' sha256 digest chain so every checkpoint marks a
  *verified epoch*.
- ``"shadow"`` — digest, plus an SDC cross-check: deterministically
  sampled chunks re-execute through a second already-compiled
  executable (the pow2-cache twin — same semantics, different
  compiled program) and the resulting state digests must agree. By
  the exactness laws any disagreement is hardware corruption or a
  real bug — never silent either way.

On detection, recovery is **deterministic rollback** (runner.py
:meth:`VerifiedRunMixin.run_verified`): restore the last verified
snapshot, discard the tainted trace rows, and re-run — the recovered
run is bit-identical to an uninjected run (the detection law,
tests/test_zzzzintegrity.py). The sweep service's flavor rides its
existing machinery: a violation journals an ``integrity_violation``
event and retries the affected bucket from its last verified
checkpoint, replaying the journaled dispatch-decision chain
(sweep/runner.py) — rollback of just that bucket, not the sweep.

Testing the machinery is deterministic too: the ``--inject`` chaos
grammar grows ``flip:SEED[:CHUNK[:PLANE]]`` (inject.py) — a seeded
bit-flip written into a state plane between chunks.
"""

from .checks import (VERIFY_MODES, IntegrityRow, IntegrityViolation,
                     first_guard_violation, make_guard_row,
                     validate_verify)
from .digest import (VERIFY_CHAIN_ZERO, chain_state_digest,
                     host_digests, tree_digest)
from .inject import (INJECT_GRAMMAR, FlipInjector, FlipSpec,
                     apply_flip, parse_flip)
from .runner import VerifiedRunMixin

__all__ = [
    "VERIFY_MODES", "IntegrityRow", "IntegrityViolation",
    "first_guard_violation", "make_guard_row", "validate_verify",
    "VERIFY_CHAIN_ZERO", "chain_state_digest", "host_digests",
    "tree_digest",
    "INJECT_GRAMMAR", "FlipInjector", "FlipSpec", "apply_flip",
    "parse_flip",
    "VerifiedRunMixin",
]
