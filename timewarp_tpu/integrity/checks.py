"""On-device guard invariants and their host-side decode.

An :class:`IntegrityRow` is the fixed-shape per-superstep violation
plane an engine threads through its traced scan when ``verify !=
"off"`` — the integrity analogue of obs/telemetry.py's
``TelemetryRow``, riding the same ``StepOut`` vehicle (the ``integ``
field; ``None`` when off, so the off-mode jaxpr is byte-identical to
the pre-knob engine). Every field is a *violation count* derived only
from values the superstep already computes, so a clean run carries an
all-zero plane and the checks can never perturb the emulation.

The checks are chosen for what a silent data corruption (a flipped
bit in HBM, a miscompiled kernel on one chip) actually does to this
state layout:

- ``time_regress`` — the superstep's instant ``t`` fell below the
  carried epoch ``state.time`` (a flip anywhere in the int64 time, or
  a wake/mailbox flip *downward*, drags the pop-min into the past);
- ``neg_counter`` — a never-silent cumulative counter (overflow,
  drop counts, ``delivered``, ``steps``, ``time``) went negative: the
  counters only ever accumulate non-negative deltas, so a negative
  value is a corrupted sign/high bit, not arithmetic;
- ``wake_past`` — a node's post-step wake is at or before ``t``
  (contract #5 forces every wake strictly past the node's firing
  instant; unfaulted runs only — crash deferral legitimately leaves a
  down node's wake behind the global clock);
- ``mb_neg`` — a mailbox deliver-time went negative relative to the
  epoch (kept entries are always strictly future after the rebase;
  unfaulted runs only, for the same deferral reason);
- ``restart_regress`` — the ``restart_done`` ledger un-consumed a
  restart row (it is monotone against the fault tables by
  construction).

Guard is deliberately *incomplete* — a payload-word flip changes no
invariant. The ``digest`` and ``shadow`` rungs of the ladder
(digest.py, runner.py) are the complete detectors; guard is the one
that localizes a violation to the exact superstep and field, in the
pinned TraceMismatch-style diagnostic format
(:class:`IntegrityViolation`; tests/test_zzzzintegrity.py pins it the
way tests/test_zzdiag.py pins TraceMismatch).
"""

from __future__ import annotations

from typing import Any, NamedTuple, Optional

import numpy as np

__all__ = ["VERIFY_MODES", "IntegrityRow", "IntegrityViolation",
           "validate_verify", "make_guard_row",
           "first_guard_violation", "guard_violation_error",
           "final_state_guard"]

#: the engine knob's legal values, in increasing cost order
VERIFY_MODES = ("off", "guard", "digest", "shadow")


def validate_verify(mode: str, who: str = "engine") -> str:
    """Loud knob validation — a typo'd mode must not silently run
    unverified (mirrors obs.telemetry.validate_mode)."""
    if mode not in VERIFY_MODES:
        raise ValueError(
            f"{who}: verify must be one of {VERIFY_MODES}, got "
            f"{mode!r} ('off' = zero overhead, 'guard' = on-device "
            "invariant checks, 'digest' = + per-chunk state digest, "
            "'shadow' = + sampled re-execution cross-check — "
            "docs/integrity.md)")
    return mode


class IntegrityViolation(RuntimeError):
    """A run-time state-integrity violation: a guard invariant fired,
    a state digest failed its chain, or a shadow re-execution
    disagreed. By the pinned exactness laws this is corruption or a
    real bug — never raised for a legitimate state. The message is
    held to the TraceMismatch diagnostic contract: one line naming
    the first violating superstep/chunk and field with scalar values,
    never an array dump."""


class IntegrityRow(NamedTuple):
    """One superstep's violation plane (device scalars; [B] per world
    under the batch vmap). All int32 counts — zero everywhere on a
    clean superstep."""
    time_regress: Any      # int32 — t < carried state.time
    neg_counter: Any       # int32 — negative cumulative counters
    wake_past: Any         # int32 — wake <= t (< NEVER); unfaulted only
    mb_neg: Any            # int32 — negative mailbox rel-times; unfaulted
    restart_regress: Any   # int32 — restart_done un-consumed


#: what each guard field means — rides the diagnostic so the error is
#: debuggable from its text alone
FIELD_MEANING = {
    "time_regress": "virtual time regressed below the carried epoch",
    "neg_counter": "a cumulative never-silent counter went negative",
    "wake_past": "a node wake landed at or before the superstep instant",
    "mb_neg": "a mailbox deliver-time went negative vs the epoch",
    "restart_regress": "the restart_done ledger un-consumed a row",
}


def make_guard_row(comm, t, prev_time, counters, wake, never,
                   rel_planes, prev_restart, new_restart,
                   faulted: bool) -> IntegrityRow:
    """Build one superstep's :class:`IntegrityRow` from values the
    superstep already computed — the ONE implementation both engines
    call (a drift here would split what "verified" means per engine).
    ``counters`` is the engine's cumulative-counter scalars (int32 and
    int64 mixed), ``rel_planes`` its epoch-relative mailbox/queue
    int32 planes. ``faulted`` disables the two checks that crash
    deferral legitimately violates (module docstring)."""
    import jax.numpy as jnp
    neg = jnp.int32(0)
    for c in counters:
        neg = neg + (c < 0).astype(jnp.int32)
    wake_past = jnp.int32(0)
    mb_neg = jnp.int32(0)
    if not faulted:
        wake_past = comm.all_sum(jnp.sum(
            (wake <= t) & (wake < never), dtype=jnp.int32))
        for plane in rel_planes:
            mb_neg = mb_neg + comm.all_sum(jnp.sum(
                plane < 0, dtype=jnp.int32))
    return IntegrityRow(
        time_regress=(t < prev_time).astype(jnp.int32),
        neg_counter=neg,
        wake_past=wake_past,
        mb_neg=mb_neg,
        restart_regress=jnp.sum(prev_restart & ~new_restart,
                                dtype=jnp.int32),
    )


def first_guard_violation(integ, valid, t_us,
                          n_worlds: Optional[int] = None
                          ) -> Optional[dict]:
    """Host-side decode of a traced run's stacked guard rows ([T]
    leaves; [T, B] batched): the FIRST violating superstep — earliest
    superstep index, then field order, then world — or None when the
    whole run is clean. The padded-scan tail and quiesced supersteps
    arrive zeroed (the drivers' valid mask), so they can never flag."""
    valid = np.asarray(valid)
    t_us = np.asarray(t_us)
    cols = {f: np.asarray(getattr(integ, f))
            for f in IntegrityRow._fields}

    def scan_world(world: Optional[int]):
        # vectorized: the clean-run (overwhelmingly common) case is
        # one numpy pass, not a Python loop per superstep × field —
        # this decode runs after EVERY guard-mode traced run
        m = valid if world is None else valid[:, world]
        idxs = np.nonzero(m)[0]
        if idxs.size == 0:
            return None
        sub = np.stack([cols[f][m] if world is None
                        else cols[f][m, world]
                        for f in IntegrityRow._fields])      # [F, S]
        hits = sub != 0
        step_any = hits.any(axis=0)
        if not step_any.any():
            return None
        si = int(np.argmax(step_any))       # first violating superstep
        fi = int(np.argmax(hits[:, si]))    # first field, schema order
        i = int(idxs[si])
        return {"superstep": i,
                "t": int(t_us[i] if world is None else t_us[i, world]),
                "world": world,
                "field": IntegrityRow._fields[fi],
                "value": int(sub[fi, si])}

    if n_worlds is None:
        return scan_world(None)
    hits = [h for h in (scan_world(b) for b in range(n_worlds)) if h]
    if not hits:
        return None
    return min(hits, key=lambda h: (h["superstep"],
                                    IntegrityRow._fields.index(
                                        h["field"]), h["world"]))


def final_state_guard(state, who: str) -> None:
    """The traceless driver's (``run_quiet``) guard: no per-superstep
    rows exist there, so only state-local invariants are checkable —
    every cumulative integer scalar must be non-negative. This keeps
    a ``verify != "off"`` engine from ever running *silently*
    unverified through the quiet path (the same never-silent stance
    as FusedRingEngine's refusal); per-superstep localization and the
    full invariant set need the traced drivers (docs/integrity.md)."""
    import jax
    for name in state._fields:
        if name == "states":
            continue    # the scenario pytree may legitimately hold
        #               # negative user values (e.g. gossip hop = -1)
        v = np.asarray(jax.device_get(getattr(state, name)))
        # counters/wake/time scalars (ndim grows by one per world
        # axis); the [K, N]-class planes have their own sentinels and
        # are the traced guard's business
        if v.ndim <= 1 and v.dtype.kind == "i" and v.size \
                and int(v.min()) < 0:
            raise IntegrityViolation(
                f"final state ({who}, run_quiet): verify=guard "
                f"invariant violated — {name}: {int(v.min())} "
                "(negative cumulative counter; run the traced driver "
                "for per-superstep localization)")


def guard_violation_error(hit: dict, who: str) -> IntegrityViolation:
    """The pinned diagnostic (module docstring): superstep row + field
    + scalar value + meaning, one line, both names, never an array."""
    w = "" if hit["world"] is None else f", world {hit['world']}"
    return IntegrityViolation(
        f"superstep {hit['superstep']} (t={hit['t']}{w}): {who} "
        f"verify=guard invariant violated — {hit['field']}: "
        f"{hit['value']} ({FIELD_MEANING[hit['field']]})")
