"""Rolling on-device state digests and their sha256 chain.

``tree_digest`` folds a complete engine-state pytree (EngineState,
EdgeState, or any NamedTuple-of-arrays) into ONE uint32 on-device —
a fixed-shape reduction built from the trace subsystem's own mix
(trace/hashing.py ``mix32_jnp`` + the wrapping uint32 sum), tagged
per leaf and per element index so a moved value hashes differently
from a changed one. Cost is one elementwise pass over the state —
what makes the ``digest`` verify mode's ≤10% overhead budget
realistic (bench.py ``gossip_100k_verify``).

The host side chains digests exactly the way the sweep chains trace
digests (sweep/spec.py ``chain_digest``): ``chain' = sha256(chain ||
digest)``, hex in / hex out, so a chunked, checkpointed, killed and
resumed run lands on the same chain value one uninterrupted run
computes — every sweep checkpoint whose meta carries the chain is a
*verified epoch* (sweep/runner.py).

Detection model: the digest is recomputed at every chunk **entry**
(runner.py) and compared against the value recorded at the previous
chunk's exit. The state arrays did not legitimately change in
between — so any difference is corruption of state at rest (an HBM
flip, a bad checkpoint restore), detected within the configured
cadence, before the corrupt state executes a single superstep.
"""

from __future__ import annotations

import hashlib

import numpy as np

__all__ = ["tree_digest", "fleet_digest", "host_digests",
           "VERIFY_CHAIN_ZERO", "chain_state_digest",
           "first_digest_mismatch"]


def first_digest_mismatch(got, want):
    """First world index whose digest moved, or None — the ONE
    compare idiom every digest check site uses (engine entry check,
    snapshot re-check, sweep prepare/step), with both values
    pre-formatted for the diagnostic. Returns ``(index, got_hex,
    want_hex)``."""
    g = np.asarray(got, np.uint32)
    w = np.asarray(want, np.uint32)
    bad = np.nonzero(g != w)[0]
    if bad.size == 0:
        return None
    b = int(bad[0])
    return b, f"{int(g[b]):08x}", f"{int(w[b]):08x}"

#: the state-digest chain seed (hex of 32 zero bytes — the same seed
#: convention as sweep/spec.py DIGEST_ZERO)
VERIFY_CHAIN_ZERO = "0" * 64


def _leaf_words(x):
    """A leaf as one or two flat uint32 word vectors (bit-exact:
    int64 splits into lo/hi words, 32-bit dtypes bitcast, sub-32-bit
    dtypes widen losslessly)."""
    import jax
    import jax.numpy as jnp
    from ..ops.numeric import thi, tlo
    x = jnp.asarray(x)
    if x.dtype == jnp.bool_:
        return (x.reshape(-1).astype(jnp.uint32),)
    if x.dtype.itemsize == 8:
        if x.dtype != jnp.int64:
            x = jax.lax.bitcast_convert_type(x, jnp.int64)
        f = x.reshape(-1)
        return (tlo(f), thi(f))
    if x.dtype.itemsize == 4:
        if x.dtype != jnp.uint32:
            x = jax.lax.bitcast_convert_type(x, jnp.uint32)
        return (x.reshape(-1),)
    # 8/16-bit leaves (none in the shipped states, but scenario state
    # pytrees are user-defined): widen via a uint8 view — lossless
    return (jax.lax.bitcast_convert_type(
        x, jnp.uint8).reshape(-1).astype(jnp.uint32),)


def _tree_digest(state):
    import jax
    import jax.numpy as jnp
    from ..ops.numeric import u32sum
    from ..trace.hashing import mix32_jnp
    h = jnp.uint32(0x811C9DC5)
    for i, leaf in enumerate(jax.tree.leaves(state)):
        for j, w in enumerate(_leaf_words(leaf)):
            if w.shape[0] == 0:
                continue
            idx = jnp.arange(w.shape[0], dtype=jnp.uint32)
            lh = u32sum(mix32_jnp(jnp.uint32(0xD1D0 + i),
                                  jnp.uint32(j), idx, w))
            # order-dependent fold across leaves/words: leaf identity
            # is in the tag, word position in this chain
            h = mix32_jnp(h, lh)
    return h


#: memoized jitted digest programs: jit caches on FUNCTION IDENTITY,
#: so building `jax.jit(jax.vmap(_tree_digest))` per call would hand
#: the cache a fresh vmap object every time and retrace at every
#: chunk boundary (~2500x the cached cost) — the wrappers are built
#: once, lazily (jax stays an in-function import like the rest of
#: this package)
_JITTED: dict = {}


def tree_digest(state):
    """One uint32 digest of a whole (solo) state pytree, jitted —
    cached per treedef/shape like any jitted program."""
    fn = _JITTED.get("solo")
    if fn is None:
        import jax
        fn = _JITTED["solo"] = jax.jit(_tree_digest)
    return fn(state)


def fleet_digest(state):
    """Per-world digests of a batched state (leading world axis on
    every leaf): uint32[B]."""
    fn = _JITTED.get("fleet")
    if fn is None:
        import jax
        fn = _JITTED["fleet"] = jax.jit(jax.vmap(_tree_digest))
    return fn(state)


def host_digests(state, batch=None) -> np.ndarray:
    """The host-side view every verified driver uses: uint32[1] for a
    solo state, uint32[B] for a batched one (``batch`` is the
    engine's BatchSpec or None)."""
    import jax
    if batch is None:
        return np.asarray([jax.device_get(tree_digest(state))],
                          np.uint32)
    return np.asarray(jax.device_get(fleet_digest(state)), np.uint32)


def chain_state_digest(prev_hex: str, digest) -> str:
    """Fold one uint32 state digest into a running sha256 chain (hex
    in, hex out) — the incremental form that survives chunking,
    checkpoints, and resume (module docstring)."""
    return hashlib.sha256(
        bytes.fromhex(prev_hex)
        + int(digest).to_bytes(4, "little")).hexdigest()
