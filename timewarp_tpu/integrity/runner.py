"""The verified chunked driver: detect, roll back, re-run, bit-exact.

``run_verified`` is ``run_stream``/``run_controlled``'s self-checking
sibling: the run executes one jitted chunk at a time, and around every
chunk the engine's ``verify`` mode is enforced —

1. **entry digest** (``digest``/``shadow``, every chunk): the state
   digest is recomputed and compared against the value recorded at
   the previous chunk's exit. The arrays did not legitimately change
   between chunks, so a mismatch is corruption of state at rest —
   caught before the corrupt state runs a superstep. This check is
   one elementwise pass over the state, so it is NOT cadence-gated:
   gating it would let a flip at an unchecked boundary be absorbed
   into the next recorded digest and go undetected forever. The
   detection window is therefore one chunk — the configured cadence
   unit of the detection law.
2. **guard** (all non-off modes): the chunk's traced scan carries the
   on-device invariant plane (checks.py); the engine's ``run`` raises
   :class:`~timewarp_tpu.integrity.checks.IntegrityViolation` naming
   the first violating superstep + field.
3. **shadow** (``shadow``, every ``cadence``-th chunk — the
   deterministic sampling knob for the one genuinely expensive
   check): the chunk re-executes from its pre-state through the
   pow2-cache twin — the
   same semantics compiled as a *different* executable (the scan pad
   is the drivers' only static input, so doubling it lands in a
   different jit cache entry while the masked tail keeps results
   bit-identical) — and the two post-states' digests must agree. By
   the exactness laws a disagreement is compute corruption (an SDC in
   one execution) or a real bug; either way it is never silent.

On any detection the driver **rolls back deterministically**: restore
the last verified snapshot (state + trace-row high-water marks),
discard the tainted rows, and re-run. The emulation is a pure
function of state and seed, so the recovered run is bit-identical —
states, traces, digests, checkpoints — to a run that was never
corrupted: the detection law (tests/test_zzzzintegrity.py). A
violation that survives ``max_rollbacks`` consecutive rollbacks of
the same chunk is persistent (bad memory cell, real logic bug) and
re-raises loudly rather than looping forever.

``verify="off"`` still runs the plain chunked loop (no checks, no
digests) — the apples-to-apples baseline the bench's
``verify_overhead_frac`` divides by.
"""

from __future__ import annotations

import numpy as np

__all__ = ["VerifiedRunMixin"]


class VerifiedRunMixin:
    """``verify=`` wiring + the self-verifying chunked driver (module
    docstring). Host state only: an engine with ``verify="off"``
    lowers byte-identical jaxprs to the pre-knob engine (the guard
    plane is a ``None`` StepOut field, exactly like telemetry)."""

    #: the engine's verify mode ("off" | "guard" | "digest" | "shadow")
    verify = "off"
    #: scan-pad multiplier for the pow2-cache shadow twin (always a
    #: pow2, so padded_scan's masked tail keeps results identical
    #: while the jit cache compiles a distinct executable)
    _pad_mult = 1
    #: the last run_verified call's integrity record (dict)
    last_run_integrity = None

    def _bind_verify(self, verify: str) -> None:
        from .checks import validate_verify
        self.verify = validate_verify(verify, type(self).__name__)

    def _capture_integrity(self, ys) -> None:
        """Host-side decode of a traced run's guard plane: raise the
        pinned TraceMismatch-style :class:`IntegrityViolation` on the
        FIRST violating superstep + field — loud, never silent, in
        any non-off mode (the ``run_verified`` driver catches it and
        rolls back; a plain ``run`` surfaces it to the caller)."""
        if self.verify == "off" or ys is None \
                or getattr(ys, "integ", None) is None:
            return
        from .checks import first_guard_violation, guard_violation_error
        batch = getattr(self, "batch", None)
        hit = first_guard_violation(
            ys.integ, np.asarray(ys.valid), np.asarray(ys.t),
            None if batch is None else batch.B)
        if hit is not None:
            raise guard_violation_error(hit, type(self).__name__)

    # -- digests ---------------------------------------------------------

    def _state_digests(self, state) -> np.ndarray:
        """uint32[1] (solo) / uint32[B] (batched) digest view."""
        from .digest import host_digests
        return host_digests(state, getattr(self, "batch", None))

    def _shadow_rerun(self, budget, pre_state):
        """Re-execute one chunk from ``pre_state`` through the
        pow2-cache twin; returns the twin's final state. The primary
        chunk's host-side artifacts (stats, telemetry, metrics
        stream) are shielded — the shadow is a check, not a run."""
        saved = (self.last_run_stats, self.last_run_telemetry,
                 getattr(self, "metrics", None),
                 getattr(self, "last_run_flight", None),
                 getattr(self, "flight_out", None))
        self.metrics = None
        self.flight_out = None
        self._pad_mult = 2
        try:
            fin, _ = self.run(budget, state=pre_state)
        finally:
            self._pad_mult = 1
            (self.last_run_stats, self.last_run_telemetry,
             self.metrics, self.last_run_flight,
             self.flight_out) = saved
        return fin

    # -- the driver ------------------------------------------------------

    def run_verified(self, budgets, state=None, *, chunk: int = 64,
                     cadence: int = 1, inject=None,
                     max_rollbacks: int = 3, on_quiesce=None):
        """Run to quiescence/budget under the engine's ``verify``
        mode, chunk by chunk, rolling back to the last verified
        snapshot on any detection (module docstring). Accepts the
        same budget forms as ``run`` (int; batched engines also a
        per-world vector) and returns ``(final_state, trace)`` —
        batched engines a per-world trace list — exactly like
        ``run``. ``inject`` is the deterministic-corruption test hook
        (integrity/inject.py ``FlipInjector``): called as
        ``inject(chunk_idx, state)`` between chunks, it may return a
        corrupted replacement state. ``on_quiesce(b, state)`` fires
        exactly once per world (``b=0`` solo), the moment the world
        has quiesced or exhausted its budget at a VERIFIED boundary —
        evaluated on committed states only and before the injection
        hook, so a rolled-back chunk can never fire (or double-fire)
        it: the rollback × streaming contract
        (tests/test_zzzzzzspec.py). The integrity record lands on
        ``last_run_integrity`` (and the digest chain on
        ``last_run_stats['digest_chain']``)."""
        from ..trace.events import SuperstepTrace
        from .checks import IntegrityViolation
        from .digest import VERIFY_CHAIN_ZERO, chain_state_digest
        mode = self.verify
        if chunk < 1:
            raise ValueError(f"chunk must be >= 1, got {chunk}")
        if cadence < 1:
            raise ValueError(f"cadence must be >= 1, got {cadence}")
        batch = getattr(self, "batch", None)
        nworld = 1 if batch is None else batch.B
        if batch is not None:
            budgets = np.broadcast_to(
                np.asarray(budgets, np.int64), (batch.B,)).copy()
        else:
            budgets = int(budgets)
        if np.min(budgets) < 0:
            raise ValueError("step budgets must be >= 0")
        st = state if state is not None else self.init_state()
        start = np.asarray(_get(st.steps), np.int64)
        rows = [[] for _ in range(nworld)]
        chunk_stats, frame_chunks, flight_chunks = [], [], []
        self.last_run_telemetry = None
        self.last_run_flight = None
        # cleared at entry: a run that RAISES (persistent corruption)
        # must not leave a previous run's record for callers to
        # misattribute
        self.last_run_integrity = None
        digest_on = mode in ("digest", "shadow")
        vdig = self._state_digests(st) if digest_on else None
        chain = [VERIFY_CHAIN_ZERO] * nworld
        #: last verified point: (state, per-world row counts)
        snap = (st, [0] * nworld)
        violations: list = []
        rollbacks = checks = 0
        consecutive = 0
        metrics = getattr(self, "metrics", None)

        def record(v: dict):
            violations.append(v)
            if metrics is not None:
                # "kind" would collide with the metrics line's own
                # kind field — the violation's kind rides as "check"
                metrics.event("integrity_violation",
                              label=self.metrics_label, **{
                                  ("check" if k == "kind" else k): val
                                  for k, val in v.items()
                                  if isinstance(val, (int, str))})

        def rollback(v: dict):
            nonlocal st, rollbacks, consecutive
            record(v)
            rollbacks += 1
            consecutive += 1
            if consecutive > max_rollbacks:
                raise IntegrityViolation(
                    f"{self.metrics_label}: chunk {v['chunk']} failed "
                    f"verification {consecutive} consecutive times "
                    f"({v.get('kind', 'guard')}) — the corruption is "
                    "persistent (bad memory / real bug), rollback "
                    "cannot converge (docs/integrity.md)")
            st = snap[0]
            for b in range(nworld):
                del rows[b][snap[1][b]:]
            if digest_on:
                # the restored snapshot must still MATCH the recorded
                # verified digest — never re-anchor the baseline from
                # it: an in-place corruption (HBM bit rot) hits the
                # live state and the snapshot's shared buffers alike,
                # and re-deriving vdig from the corrupt snapshot
                # would silently adopt the corruption as truth. A
                # snapshot that fails its own record is unrecoverable
                # in-memory — escalate to the on-disk verified-epoch
                # model (the sweep's, sweep/runner.py).
                from .digest import first_digest_mismatch
                hit = first_digest_mismatch(self._state_digests(st),
                                            vdig)
                if hit is not None:
                    bad, got_h, want_h = hit
                    raise IntegrityViolation(
                        f"{self.metrics_label}: chunk {v['chunk']} "
                        f"world {bad}: the last verified in-memory "
                        f"snapshot fails its recorded digest "
                        f"({got_h} != {want_h}) — resident state "
                        "corrupted in place; restore from an on-disk "
                        "verified checkpoint (sweep --state-verify "
                        "digest, docs/integrity.md)")
            if metrics is not None:
                metrics.emit("integrity", label=self.metrics_label,
                             mode=mode, chunk=int(v["chunk"]),
                             event="rollback")

        emitted = np.zeros(nworld, bool)
        ci = 0
        while True:
            _, remaining, active = self._controlled_progress(
                st, budgets, start)
            act = np.atleast_1d(np.asarray(active))
            newly = ~act & ~emitted
            if newly.any() and digest_on:
                # the emission below promises a VERIFIED state: an
                # in-place corruption since the last commit (the
                # digest mode's whole threat model — e.g. a corrupted
                # wake flipping world_active) must not fire the
                # exactly-once callback with a corrupt state, so the
                # entry digest check runs FIRST on quiesce
                # transitions (rare — once per world; the regular
                # every-chunk entry check below is untouched)
                from .digest import first_digest_mismatch
                hit = first_digest_mismatch(self._state_digests(st),
                                            vdig)
                if hit is not None:
                    bad, got_h, want_h = hit
                    rollback({
                        "chunk": ci, "kind": "entry_digest",
                        "world": bad if batch is not None else None,
                        "expected": want_h, "got": got_h})
                    continue
            for b in np.nonzero(newly)[0]:
                # `st` here is the last VERIFIED state (rollback
                # restores it before the loop re-enters, and the
                # digest guard above re-checks it at rest), so a
                # tainted chunk can never quiesce a world — and the
                # emitted ledger makes the callback exactly-once even
                # across rollbacks of later chunks
                emitted[int(b)] = True
                if on_quiesce is not None:
                    on_quiesce(int(b), st)
            if not np.any(active):
                break
            if inject is not None:
                mut = inject(ci, st)
                if mut is not None:
                    st = mut
            due = (ci % cadence == 0)
            if digest_on:
                checks += 1
                from .digest import first_digest_mismatch
                hit = first_digest_mismatch(self._state_digests(st),
                                            vdig)
                if hit is not None:
                    bad, got_h, want_h = hit
                    rollback({
                        "chunk": ci, "kind": "entry_digest",
                        "world": bad if batch is not None else None,
                        "expected": want_h, "got": got_h})
                    continue
            pre = st
            if batch is not None:
                budget = np.where(active,
                                  np.minimum(remaining, chunk), 0)
            else:
                budget = int(min(int(remaining), chunk))
            # shield the metrics stream AND the flight-event log
            # while the chunk runs: run() flushes its `supersteps`
            # lines (and drains recorded events) internally, but THIS
            # chunk is unverified — a chunk that fails the guard or
            # the shadow compare would leave tainted (and, after the
            # re-run, duplicated) lines behind. The flush happens at
            # commit below, once the chunk is verified.
            self.metrics = None
            fout, self.flight_out = getattr(self, "flight_out",
                                            None), None
            try:
                st, tr = self.run(budget, state=st)
            except IntegrityViolation as e:
                rollback({"chunk": ci, "kind": "guard",
                          "detail": str(e)})
                continue
            finally:
                self.metrics = metrics
                self.flight_out = fout
            pstats, ptele = self.last_run_stats, self.last_run_telemetry
            pflight = self.last_run_flight
            dp = None   # post-chunk digest, reused at commit when the
            #           # shadow compare already paid for it
            if mode == "shadow" and due:
                checks += 1
                try:
                    twin = self._shadow_rerun(budget, pre)
                    ds, dp = (self._state_digests(twin),
                              self._state_digests(st))
                except IntegrityViolation as e:
                    rollback({"chunk": ci, "kind": "shadow_guard",
                              "detail": str(e)})
                    continue
                from .digest import first_digest_mismatch
                hit = first_digest_mismatch(ds, dp)
                if hit is not None:
                    bad, shadow_h, primary_h = hit
                    rollback({
                        "chunk": ci, "kind": "shadow",
                        "world": bad if batch is not None else None,
                        "primary": primary_h, "shadow": shadow_h})
                    continue
            # commit: the chunk is verified — advance the snapshot
            # (and only now flush its telemetry to the metrics
            # stream, exactly the lines run() would have flushed)
            chunk_stats.append(pstats)
            frame_chunks.append(ptele)
            flight_chunks.append(pflight)
            if metrics is not None and ptele is not None:
                metrics.superstep_chunk(self.metrics_label, ptele)
            if fout is not None and pflight is not None:
                # drain the VERIFIED chunk's events only — a rolled-
                # back chunk's events never reach the log
                if isinstance(pflight, list):
                    for b, lg in enumerate(pflight):
                        fout.write(lg, world=b)
                else:
                    fout.write(pflight)
            if batch is not None:
                for b in range(nworld):
                    rows[b].extend(tr[b].row(i)
                                   for i in range(len(tr[b])))
            else:
                rows[0].extend(tr.row(i) for i in range(len(tr)))
            if digest_on:
                vdig = dp if dp is not None \
                    else self._state_digests(st)
                chain = [chain_state_digest(chain[b], vdig[b])
                         for b in range(nworld)]
            snap = (st, [len(r) for r in rows])
            consecutive = 0
            if metrics is not None and self.verify != "off":
                # one line per chunk a check actually ran on — the
                # guard plane and the digest entry check both run
                # every chunk (only the shadow sampling is cadenced),
                # so gating this on `due` would undercount verified
                # epochs for a metrics consumer
                metrics.emit("integrity", label=self.metrics_label,
                             mode=mode, chunk=ci, event="verified")
            ci += 1

        if chunk_stats:
            self._stats_merge(chunk_stats)
        else:
            # a zero-chunk run (already quiesced, or budget 0) must
            # not leave a PREVIOUS run's stats behind for the digest
            # fields below to graft onto — that record would be a
            # chimera of old wall/superstep numbers and this run's
            # digests
            self.last_run_stats = {"supersteps": 0,
                                   "wall_seconds": 0.0, "compiles": 0,
                                   "chunks": 0,
                                   "per_chunk_compiles": []}
        if self.telemetry != "off":
            from ..obs.telemetry import concat_frames
            self.last_run_telemetry = concat_frames(frame_chunks)
        if getattr(self, "record", "off") != "off":
            from ..obs.flight import concat_flight
            self.last_run_flight = concat_flight(flight_chunks)
        self.last_run_integrity = {
            "mode": mode, "chunks": ci, "checks": checks,
            "rollbacks": rollbacks, "violations": violations,
            "state_digest": ([int(d) for d in vdig]
                             if digest_on else None),
            "digest_chain": list(chain) if digest_on else None,
        }
        if digest_on and self.last_run_stats is not None:
            # the rolling digest chains through last_run_stats — the
            # uniform place run-level facts live (obs/, RunStatsMixin)
            self.last_run_stats["state_digest"] = [int(d)
                                                   for d in vdig]
            self.last_run_stats["digest_chain"] = list(chain)
        if batch is not None:
            return st, [SuperstepTrace.from_rows(r) for r in rows]
        return st, SuperstepTrace.from_rows(rows[0])


def _get(x):
    import jax
    return jax.device_get(x)
