"""AST linter for effect programs (the generator authoring style).

Generator programs (core/effects.py) have three classic *silent* bugs
that type checkers and the interpreters themselves cannot catch:

- **TW301 — combinator without ``yield from``**: every derived
  combinator (``wait``, ``fork``, ``timeout``, …) is itself a
  generator function; calling one as a bare statement creates the
  program object and drops it — nothing runs, no error. The same slip
  as the reference's forgotten ``void`` — except Haskell's type checker
  caught it and Python does not. A combinator under plain ``yield``
  (instead of ``yield from``) hands the interpreter a generator object
  where an Effect is expected — also flagged.
- **TW302 — ``await_io``/``AwaitIO`` reachable from a pure-emulation
  entry point**: arbitrary host IO has no deterministic virtual-time
  meaning; the pure emulator rejects it at run time (interp/ref/des.py)
  but only when that code path actually executes. Revati-style
  time-warp emulation hinges on rejecting host-time escapes up front.
- **TW303/TW304 — swallowed ``ThreadKilled``**: ``kill_thread``,
  slave-subtree teardown and ``work``'s deadline all deliver
  ``ThreadKilled`` as an async exception; a handler that catches it
  (explicitly, or via a broad ``except``) without re-raising makes the
  thread unkillable. The required idiom is the one ``repeat_forever``
  uses (core/effects.py:331-332)::

      except ThreadKilled:
          raise

  An explicit catch without re-raise is an error (TW303); a broad
  handler (bare ``except``, ``Exception``, ``BaseException``) with no
  preceding ``ThreadKilled`` re-raise arm and no ``raise`` of its own
  is a warning (TW304) — ``ThreadKilled`` deliberately subclasses
  ``Exception``-adjacent bases (core/errors.py), so broad catches do
  swallow it.

Suppression: append ``# tw-lint: ignore`` (all codes) or
``# tw-lint: ignore[TW301]`` to the offending line.

Lambda bodies are exempt from TW301: ``lambda: wait(for_(sec(1)))`` is
the ProgramFn *factory* idiom ``Fork``/``schedule`` require — creating
without running is the point there.
"""

from __future__ import annotations

import ast
import inspect
import textwrap
from typing import Iterable, List, Optional

from .report import ERROR, WARNING, Finding, LintReport

__all__ = ["lint_source", "lint_program", "lint_module_programs",
           "GENERATOR_COMBINATORS"]

#: generator combinators from core/effects.py — calling any of these
#: without ``yield from`` creates-and-drops a program object
GENERATOR_COMBINATORS = frozenset({
    "wait", "virtual_time", "my_thread_id", "fork", "fork_",
    "fork_slave", "park", "unpark", "await_io", "invoke", "schedule",
    "kill_thread", "work", "start_timer", "timeout", "modify_log_name",
    "sleep_forever", "repeat_forever",
})

#: module-ish qualifiers under which attribute calls are recognized
#: (``tw.wait(...)``); bare method names like ``conn.work()`` are not
#: flagged — too collision-prone
_MODULE_QUALIFIERS = frozenset({"tw", "timewarp_tpu", "effects"})

_BROAD = frozenset({"BaseException", "Exception"})


def _combinator_name(call: ast.Call) -> Optional[str]:
    f = call.func
    if isinstance(f, ast.Name) and f.id in GENERATOR_COMBINATORS:
        return f.id
    if isinstance(f, ast.Attribute) and f.attr in GENERATOR_COMBINATORS \
            and isinstance(f.value, ast.Name) \
            and f.value.id in _MODULE_QUALIFIERS:
        return f.attr
    return None


def _is_name(node, names: Iterable[str]) -> bool:
    return (isinstance(node, ast.Name) and node.id in names) or \
        (isinstance(node, ast.Attribute) and node.attr in names)


def _suppressed(lines: List[str], lineno: int, code: str) -> bool:
    if not 1 <= lineno <= len(lines):
        return False
    line = lines[lineno - 1]
    if "tw-lint:" not in line:
        return False
    directive = line.split("tw-lint:", 1)[1].strip()
    if directive.startswith("ignore"):
        rest = directive[len("ignore"):].strip()
        if not rest:
            return True
        return code in rest.strip("[]").replace(",", " ").split()
    return False


class _Linter(ast.NodeVisitor):
    def __init__(self, name: str, filename: str, lines: List[str],
                 pure: bool, first_line: int) -> None:
        self.report = LintReport()
        self.name = name
        self.filename = filename
        self.lines = lines
        self.pure = pure
        self.first_line = first_line

    # -- plumbing --------------------------------------------------------

    def _add(self, code: str, severity: str, node: ast.AST,
             message: str) -> None:
        lineno = getattr(node, "lineno", 1)
        if _suppressed(self.lines, lineno, code):
            return
        self.report.add(Finding(
            code, severity, self.name, message,
            location=(self.filename, lineno + self.first_line - 1)))

    # -- TW301: dropped program objects ----------------------------------

    # note: the ``lambda: wait(...)`` ProgramFn-factory idiom is exempt
    # by construction — a lambda body is an expression, never an
    # ast.Expr *statement*, so neither rule below can fire inside one

    def visit_Expr(self, node: ast.Expr) -> None:
        v = node.value
        if isinstance(v, ast.Call):
            comb = _combinator_name(v)
            if comb is not None:
                self._add(
                    "TW301", ERROR, node,
                    f"'{comb}(...)' called as a bare statement: "
                    "combinators are generator functions — the program "
                    "object is created and dropped, nothing runs. Use "
                    f"'yield from {comb}(...)'")
        self.generic_visit(node)

    def visit_Yield(self, node: ast.Yield) -> None:
        v = node.value
        if isinstance(v, ast.Call):
            comb = _combinator_name(v)
            if comb is not None:
                self._add(
                    "TW301", ERROR, node,
                    f"'yield {comb}(...)' hands the interpreter a "
                    "generator object where an Effect is expected. Use "
                    f"'yield from {comb}(...)'")
        self.generic_visit(node)

    # -- TW302: host IO in a pure context --------------------------------

    def visit_Call(self, node: ast.Call) -> None:
        if self.pure:
            f = node.func
            if _is_name(f, ("await_io", "AwaitIO")):
                which = f.id if isinstance(f, ast.Name) else f.attr
                self._add(
                    "TW302", ERROR, node,
                    f"'{which}' is reachable from a pure-emulation "
                    "entry point: real host IO has no deterministic "
                    "virtual-time meaning and the pure emulator "
                    "rejects it at run time (interp/ref/des.py). Gate "
                    "it behind the real-IO interpreter or build on "
                    "timed effects only")
        self.generic_visit(node)

    # -- TW303/TW304: swallowed ThreadKilled -----------------------------

    @staticmethod
    def _handler_names(h: ast.ExceptHandler) -> List[str]:
        t = h.type
        if t is None:
            return ["<bare>"]
        nodes = t.elts if isinstance(t, ast.Tuple) else [t]
        out = []
        for x in nodes:
            if isinstance(x, ast.Name):
                out.append(x.id)
            elif isinstance(x, ast.Attribute):
                out.append(x.attr)
        return out

    @classmethod
    def _reraises(cls, body: List[ast.stmt]) -> bool:
        """Does the handler body contain a ``raise`` statement (nested
        compound statements included, nested function/class definitions
        excluded — a raise inside an inner def does not unwind this
        handler)?"""
        for stmt in body:
            if isinstance(stmt, ast.Raise):
                return True
            if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.ClassDef)):
                continue
            for field in ("body", "orelse", "finalbody"):
                if cls._reraises(getattr(stmt, field, []) or []):
                    return True
            for h in getattr(stmt, "handlers", []) or []:
                if cls._reraises(h.body):
                    return True
        return False

    def visit_Try(self, node: ast.Try) -> None:
        killed_handled = False
        for h in node.handlers:
            names = self._handler_names(h)
            reraises = self._reraises(h.body)
            if "ThreadKilled" in names:
                if not reraises:
                    self._add(
                        "TW303", ERROR, h,
                        "'except ThreadKilled' without re-raise: the "
                        "thread becomes unkillable (kill_thread, "
                        "slave teardown and work() deadlines all "
                        "deliver ThreadKilled). Re-raise it — the "
                        "repeat_forever idiom, core/effects.py:331-332")
                killed_handled = True
            elif any(nm in _BROAD or nm == "<bare>" for nm in names):
                if not killed_handled and not reraises:
                    self._add(
                        "TW304", WARNING, h,
                        f"broad 'except {'/'.join(names)}' can swallow "
                        "ThreadKilled (it is an Exception subclass); "
                        "add a preceding 'except ThreadKilled: raise' "
                        "arm or re-raise inside")
        self.generic_visit(node)


# ----------------------------------------------------------------------
# entry points
# ----------------------------------------------------------------------

def lint_source(src: str, *, name: str = "<program>", pure: bool = True,
                filename: str = "<string>",
                first_line: int = 1) -> LintReport:
    """Lint program source text. ``pure=True`` additionally flags
    ``await_io``/``AwaitIO`` (TW302) — pass False for code that only
    ever runs under the real-IO interpreter."""
    try:
        tree = ast.parse(textwrap.dedent(src))
    except SyntaxError as e:
        rep = LintReport()
        rep.add(Finding("TW300", WARNING, name,
                        f"source not parseable ({e}); program lints "
                        "skipped", location=(filename, first_line)))
        return rep
    linter = _Linter(name, filename, src.splitlines(), pure, first_line)
    linter.visit(tree)
    return linter.report


def lint_program(fn, *, pure: bool = True) -> LintReport:
    """Lint one program (or program-builder) function via its source.
    Nested defs are linted along with it — combinator misuse inside a
    locally-defined child program is the common case."""
    name = getattr(fn, "__qualname__", getattr(fn, "__name__", str(fn)))
    try:
        src = inspect.getsource(fn)
        filename = inspect.getsourcefile(fn) or "<unknown>"
        first_line = inspect.getsourcelines(fn)[1]
    except (OSError, TypeError) as e:
        rep = LintReport()
        rep.add(Finding("TW300", WARNING, name,
                        f"source unavailable ({e}); program lints "
                        "skipped"))
        return rep
    return lint_source(src, name=name, pure=pure, filename=filename,
                       first_line=first_line)


def lint_module_programs(module, *, pure: bool = True) -> LintReport:
    """Lint every function defined in ``module`` (one parse of the
    module source — nested and decorated defs included)."""
    name = getattr(module, "__name__", str(module))
    try:
        src = inspect.getsource(module)
        filename = inspect.getsourcefile(module) or "<unknown>"
    except (OSError, TypeError) as e:
        rep = LintReport()
        rep.add(Finding("TW300", WARNING, name,
                        f"module source unavailable ({e}); program "
                        "lints skipped"))
        return rep
    return lint_source(src, name=name, pure=pure, filename=filename)
