"""Lint findings and the severity-ranked report.

The scenario sanitizer's output surface: every checker in this package
(`jaxpr_lint`, `capacity`, `program_lint`, `probes`, `plan_lint`,
`determinism`) returns :class:`Finding`\\ s collected into one
:class:`LintReport`. Severity is three-valued:

- ``error``   — a determinism-contract violation the engines would only
  surface dynamically (digest mismatch, silent mailbox drop, trace-time
  crash). Engines built with ``lint="error"`` refuse to construct.
- ``warning`` — legal but wasteful or fragile (a conservative flag the
  engine pays for every superstep; a broad ``except`` that can swallow
  ``ThreadKilled``).
- ``info``    — a reported bound or note, never actionable by itself.

Suppression: scenario-level via ``Scenario.meta["lint_ignore"] =
["TW110", ...]``; source-level (AST linter) via a ``# tw-lint: ignore``
or ``# tw-lint: ignore[TW301]`` comment on the offending line
(docs/authoring.md "Lint rules").
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Iterable, List, Optional, Tuple

from ..core.errors import TimeWarpError

__all__ = ["Finding", "LintReport", "LintError",
           "ERROR", "WARNING", "INFO"]

ERROR, WARNING, INFO = "error", "warning", "info"
_RANK = {ERROR: 0, WARNING: 1, INFO: 2}


@dataclass(frozen=True)
class Finding:
    """One lint finding.

    ``code`` is stable (``TW1xx`` jaxpr contract lints, ``TW2xx``
    capacity proofs, ``TW3xx`` effect-program AST lints, ``TW4xx``
    probes, ``TW5xx`` fault-schedule lints, ``TW6xx`` sweep-pack plan
    lints, ``TW7xx`` jaxpr determinism sanitizer); messages may be
    reworded freely.
    """
    code: str
    severity: str
    subject: str          # scenario / program the finding is about
    message: str
    #: optional (filename, line) for AST findings
    location: Optional[Tuple[str, int]] = None

    def __post_init__(self):
        if self.severity not in _RANK:
            raise ValueError(f"unknown severity {self.severity!r}")

    def render(self) -> str:
        loc = ""
        if self.location is not None:
            loc = f" ({self.location[0]}:{self.location[1]})"
        return (f"[{self.severity.upper():7s}] {self.code} "
                f"{self.subject}{loc}: {self.message}")


@dataclass
class LintReport:
    """Severity-ranked collection of findings."""
    findings: List[Finding] = field(default_factory=list)

    def add(self, finding: Finding) -> None:
        self.findings.append(finding)

    def extend(self, other: "LintReport") -> "LintReport":
        self.findings.extend(other.findings)
        return self

    # -- views -----------------------------------------------------------

    @property
    def errors(self) -> List[Finding]:
        return [f for f in self.findings if f.severity == ERROR]

    @property
    def warnings(self) -> List[Finding]:
        return [f for f in self.findings if f.severity == WARNING]

    @property
    def infos(self) -> List[Finding]:
        return [f for f in self.findings if f.severity == INFO]

    @property
    def ok(self) -> bool:
        """True when no error-severity finding is present."""
        return not self.errors

    def ranked(self) -> List[Finding]:
        """Findings most-severe first (stable within a severity)."""
        return sorted(self.findings, key=lambda f: _RANK[f.severity])

    def codes(self) -> List[str]:
        return [f.code for f in self.findings]

    def filtered(self, ignore: Iterable[str]) -> "LintReport":
        """A new report without the findings whose code is in ``ignore``
        (the ``meta["lint_ignore"]`` suppression path)."""
        ig = set(ignore)
        return LintReport([f for f in self.findings if f.code not in ig])

    # -- rendering -------------------------------------------------------

    def render(self) -> str:
        if not self.findings:
            return "lint: clean (0 findings)"
        lines = [f.render() for f in self.ranked()]
        lines.append(
            f"lint: {len(self.errors)} error(s), "
            f"{len(self.warnings)} warning(s), {len(self.infos)} info")
        return "\n".join(lines)

    def to_json(self) -> dict:
        return {
            "errors": len(self.errors),
            "warnings": len(self.warnings),
            "infos": len(self.infos),
            "findings": [
                {"code": f.code, "severity": f.severity,
                 "subject": f.subject, "message": f.message,
                 **({"file": f.location[0], "line": f.location[1]}
                    if f.location else {})}
                for f in self.ranked()],
        }


class LintError(TimeWarpError):
    """Raised by ``lint="error"`` engine construction (and the CLI lint
    gate) when a report carries error-severity findings. Carries the
    full report as ``.report``."""

    def __init__(self, report: LintReport, who: str = "lint") -> None:
        self.report = report
        super().__init__(f"{who}: failed lint\n{report.render()}")
