"""Fault-schedule lint rules (TW5xx) — the sanitizer's chaos arm.

A :class:`~timewarp_tpu.faults.schedule.FaultSchedule` is validated
for well-formedness at construction (types, ranges); the checks that
need the *scenario* — node ranges, window sanity against the engine's
single-pass deferral, reset-template cost — live here, surfaced
through the same severity-ranked :class:`~timewarp_tpu.analysis.
report.LintReport` and the same engine ``lint="error"|"warn"|"off"``
knob as the TW1xx–TW4xx scenario rules.

Rules:

- **TW501** (error): an event names a node ``>= n_nodes`` — it can
  never match a live node, so the intended fault silently does
  nothing.
- **TW502** (error): two crash windows for one node overlap **or
  touch** — the engines' single-pass deferral (faults/apply.py)
  defines suppression only for windows separated by a gap: an event
  deferred to window A's ``t_up`` lands exactly on an adjacent
  window B's ``t_down`` and fires inside B. Merge them into one
  window.
- **TW503** (error): a fault window with ``t_end <= t_start`` (crash,
  partition, or degradation) — an empty window is inert, which is
  never what the author scheduled.
- **TW504** (warning): ``reset_state`` on a scenario without
  ``init_batched`` — the reboot template is stacked per node on the
  host (the same loop ``init_state`` pays, but now twice); declare
  ``init_batched`` before running reset chaos at millions of nodes.
"""

from __future__ import annotations

from ..core.scenario import Scenario
from .report import ERROR, WARNING, Finding, LintReport

__all__ = ["lint_fault_schedule", "check_faults"]


def lint_fault_schedule(faults, scenario: Scenario) -> LintReport:
    """Run the TW5xx rules for one schedule (or every world of a
    :class:`~timewarp_tpu.faults.schedule.FaultFleet`) against
    ``scenario``."""
    from ..faults.schedule import FaultFleet
    if isinstance(faults, FaultFleet):
        rep = LintReport()
        for b, sched in enumerate(faults.schedules):
            world = lint_fault_schedule(sched, scenario)
            for f in world.findings:
                rep.add(Finding(f.code, f.severity,
                                f"{f.subject}[world {b}]", f.message,
                                f.location))
        return rep

    rep = LintReport()
    sub = scenario.name
    n = scenario.n_nodes

    def bad_node(i: int, what: str) -> None:
        if i >= n:
            rep.add(Finding(
                "TW501", ERROR, sub,
                f"{what} names node {i} but the scenario has "
                f"n_nodes={n} — the fault can never bite "
                f"(nodes are 0..{n - 1})"))

    def bad_window(lo: int, hi: int, what: str) -> None:
        if hi <= lo:
            rep.add(Finding(
                "TW503", ERROR, sub,
                f"{what} window [{lo}, {hi}) is empty "
                f"(t_end <= t_start) — an inert fault is never what "
                "was scheduled"))

    crashes = faults.crashes
    for c in crashes:
        bad_node(c.node, "crash")
        bad_window(c.t_down, c.t_up, "crash")
    by_node: dict = {}
    for c in crashes:
        if c.t_up > c.t_down:
            by_node.setdefault(c.node, []).append((c.t_down, c.t_up))
    for node, wins in by_node.items():
        wins.sort()
        for (d0, u0), (d1, u1) in zip(wins, wins[1:]):
            if d1 <= u0:
                rep.add(Finding(
                    "TW502", ERROR, sub,
                    f"crash windows [{d0}, {u0}) and [{d1}, {u1}) for "
                    f"node {node} overlap or touch — deferral is "
                    "single-pass (faults/apply.py): an event deferred "
                    f"to {u0} would fire inside the next window; "
                    "merge them into one window"))

    for p in faults.partitions:
        for g in p.groups:
            for i in g:
                bad_node(i, "partition group")
        bad_window(p.t_start, p.t_end, "partition")

    for lw in faults.link_windows:
        for side_name in ("src", "dst"):
            side = getattr(lw, side_name)
            if side:
                for i in side:
                    bad_node(i, f"degradation {side_name}")
        bad_window(lw.t_start, lw.t_end, "degradation")

    for s in faults.skews:
        bad_node(s.node, "clock skew")

    if any(c.reset_state for c in crashes) \
            and scenario.init_batched is None:
        rep.add(Finding(
            "TW504", WARNING, sub,
            "reset_state crash on a scenario without init_batched: "
            "the reboot template is built by a per-node host loop "
            "(fine at test scale; declare init_batched before "
            "running reset chaos at large n_nodes)"))
    return rep


def check_faults(faults, scenario: Scenario, mode: str, *,
                 who: str = "engine"):
    """Construction-time hook for fault-capable engines — the TW5xx
    twin of :func:`~timewarp_tpu.analysis.check_scenario`, under the
    same ``lint`` knob contract ("off" skips, "error" raises
    :class:`~timewarp_tpu.analysis.report.LintError`, "warn" logs)."""
    import logging

    from . import LINT_MODES
    from .report import LintError
    if mode == "off":
        return None
    if mode not in LINT_MODES:
        raise ValueError(
            f"lint must be one of {LINT_MODES}, got {mode!r}")
    report = lint_fault_schedule(faults, scenario)
    if mode == "error" and not report.ok:
        raise LintError(report, who=who)
    log = logging.getLogger("timewarp_tpu.analysis")
    for f in report.errors:
        log.warning("%s: %s", who, f.render())
    for f in report.warnings:
        log.info("%s: %s", who, f.render())
    return report
