"""Scenario sanitizer: static analysis for both authoring styles.

The framework's determinism contract (core/scenario.py:28-47) is
checkable *before* any engine run:

- :mod:`.jaxpr_lint` — abstract-traces ``Scenario.step`` and checks
  host-escape primitives, time-dtype discipline, outbox conformance
  and the declared-flag dataflow (TW1xx).
- :mod:`.capacity` — static mailbox-capacity proofs over
  ``static_dst`` topologies; reported bounds for dynamic ones (TW2xx).
- :mod:`.program_lint` — AST lints for generator effect programs:
  dropped combinator calls, host IO in pure contexts, swallowed
  ``ThreadKilled`` (TW3xx).
- :mod:`.probes` — seeded permutation probe for ``commutative_inbox``,
  the one flag dataflow cannot verify (TW4xx).
- :mod:`.plan_lint` — fleet-scale pre-flight verification of sweep
  packs / serve submissions: predicted bucket plans, engine-refusal
  mirrors, pad-growth rebuild warnings (TW6xx).
- :mod:`.determinism` — jaxpr-level bit-exactness threats (unordered
  float reductions, platform-dependent transcendentals, non-threefry
  randomness, host callbacks in traced engine code) and the generic
  off-mode neutrality proof (TW7xx).

Every engine runs :func:`check_scenario` at construction under its
``lint="error"|"warn"|"off"`` knob (default ``"warn"``); the CLI
exposes ``timewarp-tpu lint`` over every shipped model and a
``--lint`` flag on runs. See docs/authoring.md "Lint rules" for the
full rule table and suppression mechanics.
"""

from __future__ import annotations

import logging

from ..core.scenario import Scenario
from .capacity import (lint_capacity, lint_capacity_faulted,
                       max_delay_us, worst_case_fan_in)
from .determinism import (lint_engine_jaxpr, lint_step_determinism,
                          prove_mode_neutrality,
                          scan_jaxpr_determinism)
from .fault_lint import check_faults, lint_fault_schedule
from .jaxpr_lint import HOST_ESCAPE_PRIMITIVES, lint_step_jaxpr
from .plan_lint import (lint_pack, lint_pack_json, lint_pack_path,
                        lint_run_config)
from .probes import probe_commutative_inbox
from .program_lint import (GENERATOR_COMBINATORS, lint_module_programs,
                           lint_program, lint_source)
from .report import (ERROR, INFO, WARNING, Finding, LintError,
                     LintReport)

__all__ = [
    "Finding", "LintReport", "LintError",
    "ERROR", "WARNING", "INFO",
    "lint_scenario", "check_scenario", "LINT_MODES",
    "lint_fault_schedule", "check_faults",
    "lint_step_jaxpr", "lint_capacity", "worst_case_fan_in",
    "lint_capacity_faulted", "max_delay_us",
    "lint_pack", "lint_pack_json", "lint_pack_path",
    "lint_run_config",
    "lint_step_determinism", "lint_engine_jaxpr",
    "prove_mode_neutrality", "scan_jaxpr_determinism",
    "probe_commutative_inbox",
    "lint_program", "lint_source", "lint_module_programs",
    "HOST_ESCAPE_PRIMITIVES", "GENERATOR_COMBINATORS",
]

log = logging.getLogger("timewarp_tpu.analysis")

#: valid values of the engines' construction-lint knob
LINT_MODES = ("error", "warn", "off")


def lint_scenario(scenario: Scenario, *, probe: bool = False,
                  seed: int = 0) -> LintReport:
    """Run every scenario-level checker. ``probe=True`` adds the
    concrete ``commutative_inbox`` permutation probe (executes the step
    a handful of times — engines skip it at construction; the CLI
    ``lint`` subcommand runs it by default).

    Findings whose code appears in ``scenario.meta["lint_ignore"]``
    are suppressed (the documented opt-out, docs/authoring.md)."""
    rep = LintReport()
    rep.extend(lint_step_jaxpr(scenario))
    rep.extend(lint_step_determinism(scenario))
    rep.extend(lint_capacity(scenario))
    if probe:
        rep.extend(probe_commutative_inbox(scenario, seed=seed))
    ignore = ()
    if isinstance(scenario.meta, dict):
        ignore = tuple(scenario.meta.get("lint_ignore", ()))
    return rep.filtered(ignore) if ignore else rep


def check_scenario(scenario: Scenario, mode: str, *,
                   who: str = "engine"):
    """Construction-time hook shared by every engine.

    ``mode="off"`` returns None without looking at the scenario (the
    bit-for-bit compatibility path). ``"error"`` raises
    :class:`LintError` on any error-severity finding. ``"warn"`` (the
    default everywhere) logs errors at WARNING and perf findings at
    INFO, then lets construction proceed. The (probe-free) report is
    cached on the scenario object — engines are constructed far more
    often than scenarios are built."""
    if mode == "off":
        return None
    if mode not in LINT_MODES:
        raise ValueError(
            f"lint must be one of {LINT_MODES}, got {mode!r}")
    report = getattr(scenario, "_lint_cache", None)
    if report is None:
        report = lint_scenario(scenario, probe=False)
        try:
            scenario._lint_cache = report
        except Exception:  # noqa: BLE001 — cache is best-effort
            pass
    if mode == "error" and not report.ok:
        raise LintError(report, who=who)
    for f in report.errors:
        log.warning("%s: %s", who, f.render())
    for f in report.warnings:
        log.info("%s: %s", who, f.render())
    return report
