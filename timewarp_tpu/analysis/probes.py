"""Seeded permutation probe for the ``commutative_inbox`` flag.

``commutative_inbox=True`` is the one declaration pure jaxpr dataflow
cannot validate: whether a step's *result* is invariant to inbox slot
order is a semantic property, not a structural one. The engines lean
hard on the flag — they skip the contract-#2 inbox sort, present
delivered slots in raw mailbox-row order (engine.py step 3), and turn
freed slots into unsorted holes — so a falsely-declared flag produces
engine-vs-oracle digest divergence with no local symptom.

The probe checks the property the cheap way: execute the step
concretely on a handful of nodes with randomized inboxes and compare
the full result (state', outbox, next_wake) bit-for-bit across seeded
slot permutations. Invalid slots carry the canonical padding every
interpreter presents (src 0, time NEVER, payload 0 — engine.py step 3
/ superstep.py), and the padding permutes *with* the slots, exactly
the variation the engine's raw-mailbox-order inbox exhibits. A probe
is evidence, not proof — but three rounds × three permutations ×
several nodes catches every first-slot / positional dependence, which
is the realistic bug class.
"""

from __future__ import annotations

import numpy as np

from ..utils import jaxconfig  # noqa: F401

import jax
import jax.numpy as jnp

from ..core.rng import fire_bits, seed_words
from ..core.scenario import NEVER, Inbox, Scenario
from .report import ERROR, WARNING, Finding, LintReport

__all__ = ["probe_commutative_inbox"]


def _tree_equal(a, b) -> bool:
    la, ta = jax.tree.flatten(a)
    lb, tb = jax.tree.flatten(b)
    if ta != tb:
        return False
    for x, y in zip(la, lb):
        x, y = np.asarray(x), np.asarray(y)
        if x.dtype != y.dtype or x.shape != y.shape \
                or not np.array_equal(x, y):
            return False
    return True


def probe_commutative_inbox(sc: Scenario, *, seed: int = 0,
                            rounds: int = 3, max_nodes: int = 4,
                            now_us: int = 1_000_000) -> LintReport:
    """Empty report when the scenario does not declare
    ``commutative_inbox``; otherwise TW401 errors for every node whose
    step result changed under an inbox slot permutation."""
    rep = LintReport()
    if not sc.commutative_inbox:
        return rep
    name = sc.name
    K, P, n = sc.mailbox_cap, sc.payload_width, sc.n_nodes
    rng = np.random.default_rng(seed)
    s0, s1 = seed_words(seed)
    nodes = list(range(min(n, max_nodes)))

    try:
        states = [jax.tree.map(jnp.asarray, sc.init(i)[0]) for i in nodes]
    except Exception as e:  # noqa: BLE001 — lint must not crash callers
        rep.add(Finding("TW400", WARNING, name,
                        f"commutative-inbox probe skipped: init "
                        f"failed ({e!r})"))
        return rep

    now = jnp.int64(now_us)
    for r in range(rounds):
        # a partially-filled inbox with distinct times/srcs/payloads —
        # distinctness maximizes the chance an order dependence shows
        # (K == 1 still probes: the one valid slot moves among padding)
        n_valid = 1 if K == 1 else int(rng.integers(2, K + 1))
        valid = np.zeros(K, bool)
        valid[:n_valid] = True
        times = np.full(K, NEVER, np.int64)
        times[:n_valid] = np.sort(
            rng.choice(np.arange(1, now_us, dtype=np.int64),
                       size=n_valid, replace=False))
        srcs = np.zeros(K, np.int32)
        srcs[:n_valid] = rng.integers(0, n, size=n_valid)
        pay = np.zeros((K, P), np.int32)
        pay[:n_valid] = rng.integers(0, 8, size=(n_valid, P))
        if not sc.inbox_src:
            srcs[:] = 0         # the engines elide src for this flag
        perms = [np.arange(K)] + [rng.permutation(K) for _ in range(2)]

        for node, state in zip(nodes, states):
            nid = jnp.int32(node)
            key = None
            if sc.needs_key:
                key = fire_bits(s0, s1, nid, now)
            ref = None
            for p_i, perm in enumerate(perms):
                inbox = Inbox(
                    valid=jnp.asarray(valid[perm]),
                    src=jnp.asarray(srcs[perm]),
                    time=jnp.asarray(times[perm]),
                    payload=jnp.asarray(pay[perm]),
                )
                try:
                    got = sc.step(state, inbox, now, nid, key)
                except Exception as e:  # noqa: BLE001
                    rep.add(Finding(
                        "TW400", WARNING, name,
                        f"commutative-inbox probe skipped: step failed "
                        f"on a probe inbox ({e!r})"))
                    return rep
                if ref is None:
                    ref = got
                elif not _tree_equal(ref, got):
                    rep.add(Finding(
                        "TW401", ERROR, name,
                        "commutative_inbox=True but the step result "
                        f"depends on inbox slot order (node {node}, "
                        f"probe round {r}, permutation {p_i}, seed "
                        f"{seed}): engines skip the contract-#2 inbox "
                        "sort for this flag, so this diverges from "
                        "the oracle. Declare commutative_inbox=False "
                        "or make the step a commutative reduction"))
                    return rep
    return rep
