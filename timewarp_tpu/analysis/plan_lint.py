"""Plan lint (TW6xx): fleet-scale pre-flight verification of sweep
packs and serve submissions.

Everything the sweep service or the serving frontend would reject
*mid-bucket* — after JSON parsing succeeded, after engines compiled —
is statically decidable from the pack alone, because every refusal in
the runtime path (engine.py window validation, speculation floor
checks, the bucketer's shape keys) is a pure function of the config.
This module mirrors those decisions without building a single engine:

- **TW600** (error) — a pack entry does not parse (the PACK_GRAMMAR
  contract, sweep/spec.py): unknown family/param, controller x
  speculate, malformed link/fault/speculate specs, duplicate run_ids.
  Parse failures become findings instead of exceptions so one broken
  entry never hides the rest of the report.
- **TW601** (info) — the predicted bucket plan: worlds -> buckets
  (= engine builds), fleet widths, resolved windows, fault-pad
  shapes. The number the zero-recompile serving contract (r20) is
  about, made visible before anything compiles.
- **TW602** (error) — an explicit window the engine would refuse:
  wider than the link floor, *degraded by the config's own fault
  schedule* for static configs (a degrade window undercutting the
  declared floor is the classic mid-bucket surprise); controller /
  speculate configs validate against the undegraded floor exactly as
  the engine does (the device-side clamp covers degradation,
  docs/dispatch.md, docs/speculation.md).
- **TW603** (error) — a ``speculate="fixed:W"`` horizon that provably
  cannot exceed its conservative floor: at or below the floor the
  static window already proves exactness, and the engine refuses at
  construction — mid-bucket, after the pack was accepted.
- **TW604** (error) — speculation on an insert strategy that bakes
  the window into kernel arithmetic (``TW_INSERT=pallas|interpret``):
  no dynamic clamp point, refused by the engine
  (docs/speculation.md); the lint resolves the strategy exactly as
  the runtime would, environment override included.
- **TW605** (warning) — pad-growth rebuilds: a bucket whose
  fault-table row counts GROW along pack order. A batch sweep pads
  once, but serving-style mid-bucket admission (docs/serving.md)
  compiles at the first world's pad — a later, wider schedule forces
  the rebuild the r20 zero-recompile contract exists to prevent.
  Front-load the widest schedule (or pre-pad with ``pad``).
- **TW606** (warning) — occupancy skew under first-fit packing: a
  bucket whose forecast per-world supersteps (declared budgets — the
  honest no-artifact predictor, timewarp_tpu/pack/predict.py) spread
  wider than :data:`TW606_SPREAD`. Short worlds quiesce early and
  their slots idle budget-masked while every chunk still pays the
  longest member's pow2 scan pad; ``--pack predicted`` re-sorts each
  shape group best-fit-decreasing to equalize per-bucket quiescence
  horizons (docs/sweeps.md "Predictive packing").

Per config, the plan lint also runs the scenario sanitizer the
engines would (jaxpr contract + capacity, cached per family/params),
the TW5xx fault lints against the config's own schedule, and the
fault-aware capacity proof (TW205/TW206, capacity.py) at the
config's resolved window — so ``timewarp-tpu lint-pack`` is the whole
pre-flight, not just the plan rules.

Entry points: :func:`lint_run_config` (one parsed config — the serve
admission gate), :func:`lint_pack` (a parsed pack — the sweep prepare
gate), :func:`lint_pack_json` / :func:`lint_pack_path` (raw JSON —
the CLI, where parse failures must become TW600 findings).
"""

from __future__ import annotations

import json
from functools import lru_cache
from typing import Any, List, Optional, Tuple

from ..sweep.spec import (RunConfig, SweepConfigError, SweepPack,
                          build_scenario, resolve_window)
from .capacity import lint_capacity_faulted
from .report import ERROR, INFO, WARNING, Finding, LintReport

__all__ = ["lint_run_config", "lint_pack", "lint_pack_json",
           "lint_pack_path", "TW606_SPREAD"]

#: TW606 threshold: warn when a first-fit bucket's forecast
#: supersteps spread (1 - shortest/longest) exceeds this — i.e. the
#: shortest member is forecast to finish in under half the longest
#: member's horizon, leaving its slot budget-masked for the rest
TW606_SPREAD = 0.5


@lru_cache(maxsize=64)
def _scenario(family: str, params: Tuple[Tuple[str, Any], ...]):
    """Scenario build cache: a pack has few distinct (family, params)
    shapes but many worlds, and admission lints per submission — the
    cached object also carries ``_lint_cache`` (analysis/__init__.py),
    so the jaxpr trace happens once per shape, not once per world."""
    return build_scenario(family, params)


def _scenario_report(sc) -> LintReport:
    from . import lint_scenario
    rep = getattr(sc, "_lint_cache", None)
    if rep is None:
        rep = lint_scenario(sc, probe=False)
        try:
            sc._lint_cache = rep
        except Exception:  # noqa: BLE001 — cache is best-effort
            pass
    return rep


def _fault_rows(cfg: RunConfig) -> Tuple[int, int, int]:
    """The config's fault-table row counts (crash, partition,
    link-window), pad included — the shape component mid-bucket
    admission must not grow (TW605)."""
    sched = cfg.parse_faults()
    if sched is None:
        return (0, 0, 0)
    return (len(sched.crashes) + sched.pad[0],
            len(sched.partitions) + sched.pad[1],
            len(sched.link_windows) + sched.pad[2])


def _resolved_insert() -> Tuple[str, bool]:
    """The insert strategy a sweep/serve JaxEngine would resolve right
    now (env override included) and whether it threads the dynamic
    window — the lint must predict the runtime's refusal, so it asks
    the same resolver (interp/jax_engine/pallas_insert.py)."""
    from ..interp.jax_engine.pallas_insert import resolve_insert
    _, resolved, _, _ = resolve_insert(None, honor_env=True,
                                       who="plan lint")
    return resolved, resolved not in ("pallas", "interpret")


def lint_run_config(cfg: RunConfig, *, deep: bool = True) -> LintReport:
    """Every statically decidable refusal for ONE config: the TW6xx
    window/speculation mirrors of engine validation, plus (``deep``)
    the scenario sanitizer, the TW5xx fault lints, and the
    fault-aware capacity proof at the config's resolved window.
    Scenario-level ``meta["lint_ignore"]`` suppression applies to the
    whole report (the documented opt-out, docs/authoring.md)."""
    rep = LintReport()
    who = f"config {cfg.run_id!r}"
    try:
        link = cfg.parse_link()
        sched = cfg.parse_faults()
    except SweepConfigError as e:
        rep.add(Finding("TW600", ERROR, who, str(e)))
        return rep

    link_floor = int(link.min_delay_us)
    degraded = sched.min_delay_floor(link_floor) if sched is not None \
        else link_floor
    dyn = cfg.controller == "auto" or cfg.speculate != "off"
    insert, dyn_ok = _resolved_insert()
    if cfg.speculate != "off" and not dyn_ok:
        rep.add(Finding(
            "TW604", ERROR, who,
            f"speculate={cfg.speculate!r} threads the dynamic "
            f"per-superstep window, but the insert strategy resolves "
            f"to {insert!r} (TW_INSERT), which bakes the window into "
            "kernel arithmetic — no clamp point, refused at engine "
            "construction; run speculation on the XLA insert "
            "strategies (docs/speculation.md)"))
    # the engine's floor choice (engine.py window validation): static
    # configs — and kernel-window engines regardless — take the
    # fault-DEGRADED floor; dynamic-window configs keep the undegraded
    # floor (the device clamp narrows per superstep)
    floor = link_floor if (dyn and dyn_ok) else degraded
    if cfg.window != "auto" and int(cfg.window) > 1 \
            and int(cfg.window) > floor:
        under = (f" (the fault schedule degrades the declared "
                 f"min_delay_us={link_floor} to {degraded})"
                 ) if floor < link_floor else ""
        rep.add(Finding(
            "TW602", ERROR, who,
            f"window={cfg.window} us exceeds the provable link floor "
            f"{floor}{under}; windowed supersteps would reorder "
            "causally dependent events and the engine refuses at "
            "construction — mid-bucket, after the pack was accepted. "
            f"Use window <= {floor}, window='auto', or speculate "
            "(docs/speculation.md)"))
    if cfg.speculate.startswith("fixed"):
        from ..speculate.plane import parse_speculate
        _, W = parse_speculate(cfg.speculate)
        spec_floor = resolve_window(cfg)
        if W is not None and W <= spec_floor:
            rep.add(Finding(
                "TW603", ERROR, who,
                f"speculate='fixed:{W}' cannot exceed its "
                f"conservative floor: the config resolves window "
                f"{spec_floor} us, and at or below the floor the "
                "static window already proves exactness — nothing to "
                "speculate; widen W past the floor or use "
                "speculate='auto' (docs/speculation.md)"))

    if not deep:
        return rep
    try:
        sc = _scenario(cfg.family, cfg.params)
    except SweepConfigError as e:
        rep.add(Finding("TW600", ERROR, who, str(e)))
        return rep
    except Exception as e:  # noqa: BLE001 — a build crash is a finding
        rep.add(Finding("TW600", ERROR, who,
                        f"scenario failed to build: {e!r}"))
        return rep
    rep.extend(_scenario_report(sc))
    if sched is not None:
        from .fault_lint import lint_fault_schedule
        rep.extend(lint_fault_schedule(sched, sc))
        rep.extend(lint_capacity_faulted(
            sc, sched, link, resolve_window(cfg), subject=who))
    ignore = ()
    if isinstance(sc.meta, dict):
        ignore = tuple(sc.meta.get("lint_ignore", ()))
    return rep.filtered(ignore) if ignore else rep


def lint_pack(pack: SweepPack, *, max_bucket: int = 64) -> LintReport:
    """The whole pre-flight for a parsed pack: per-config rules
    (:func:`lint_run_config`), the predicted bucket plan (TW601), the
    pad-growth rebuild warning (TW605), and the first-fit occupancy
    skew warning (TW606)."""
    from ..sweep.bucket import plan_buckets
    rep = LintReport()
    plannable: List[RunConfig] = []
    for cfg in pack.configs:
        r = lint_run_config(cfg)
        rep.extend(r)
        # a config whose link/faults do not even parse cannot be
        # bucketed (resolve_window would raise)
        if not any(f.code == "TW600" for f in r.errors):
            plannable.append(cfg)
    if not plannable:
        return rep
    try:
        buckets = plan_buckets(plannable, max_bucket=max_bucket)
    except (SweepConfigError, ValueError) as e:
        rep.add(Finding("TW600", ERROR, "pack",
                        f"bucket planning failed: {e}"))
        return rep
    pads = {}
    for b in buckets:
        rows = [_fault_rows(c) for c in b.configs]
        pads[b.bucket_id] = tuple(max(r[i] for r in rows)
                                  for i in range(3))
        high = rows[0]
        for c, r in zip(b.configs[1:], rows[1:]):
            if any(x > h for x, h in zip(r, high)):
                rep.add(Finding(
                    "TW605", WARNING, f"config {c.run_id!r}",
                    f"bucket {b.bucket_id}: fault tables grow from "
                    f"{high} to row counts {r} along pack order — a "
                    "batch sweep pads once, but mid-bucket admission "
                    "(serve) compiles at the first world's pad and "
                    "this world would force an engine REBUILD, "
                    "defeating the zero-recompile serving contract "
                    "(docs/serving.md). Front-load the widest "
                    "schedule or pre-pad the earlier worlds"))
            high = tuple(max(x, h) for x, h in zip(r, high))
    from ..pack.predict import predict_supersteps
    for b in buckets:
        if b.B < 2:
            continue
        preds = [predict_supersteps(c, None) for c in b.configs]
        spread = 1.0 - (min(preds) / max(preds))
        if spread > TW606_SPREAD:
            rep.add(Finding(
                "TW606", WARNING, f"bucket {b.bucket_id}",
                f"first-fit occupancy skew: forecast supersteps span "
                f"{min(preds)}..{max(preds)} (spread "
                f"{spread:.0%} > {TW606_SPREAD:.0%}) — short worlds "
                "quiesce early and idle budget-masked while every "
                "chunk pays the longest member's pow2 scan pad; "
                "re-plan with `--pack predicted` (docs/sweeps.md "
                "'Predictive packing')"))
    widths = [b.B for b in buckets]
    windows = sorted({b.window for b in buckets})
    pad_note = ", ".join(
        f"{bid}:{p}" for bid, p in pads.items() if p != (0, 0, 0))
    rep.add(Finding(
        "TW601", INFO, "pack",
        f"plan: {len(plannable)} world(s) -> {len(buckets)} bucket(s)"
        f" = {len(buckets)} engine build(s); fleet widths {widths}; "
        f"resolved windows {windows}"
        + (f"; fault pads {pad_note}" if pad_note else "")))
    return rep


def lint_pack_json(data: Any, *,
                   max_bucket: int = 64,
                   speculate_default: Optional[str] = None
                   ) -> Tuple[int, LintReport]:
    """Lint raw pack JSON: every entry that fails PACK_GRAMMAR
    parsing becomes a TW600 finding (controller x speculate, unknown
    keys, type violations — the refusals RunConfig.__post_init__
    cannot represent as a parsed config), and the parseable remainder
    is linted as a pack. Returns ``(n_entries, report)``."""
    rep = LintReport()
    if isinstance(data, dict):
        # unwrap the {"worlds": [...]} form by hand, mirroring
        # SweepPack.from_json's pack-level defaults, so ONE
        # unparseable entry becomes one finding rather than refusing
        # to look at the rest of the pack
        default_ctrl = data.get("controller")
        if speculate_default is None:
            spec = data.get("speculate")
            if isinstance(spec, str):
                speculate_default = spec
        data = data.get("worlds", data)
        if isinstance(data, list) and default_ctrl is not None:
            data = [({**d, "controller": default_ctrl}
                     if isinstance(d, dict) and "controller" not in d
                     else d) for d in data]
    if not isinstance(data, list):
        rep.add(Finding(
            "TW600", ERROR, "pack",
            "a pack file is a JSON list of config objects (or "
            "{'worlds': [...]})"))
        return 0, rep
    configs: List[RunConfig] = []
    seen = set()
    for i, d in enumerate(data):
        if speculate_default is not None and isinstance(d, dict) \
                and "speculate" not in d:
            d = {**d, "speculate": speculate_default}
        try:
            cfg = RunConfig.from_json(d, i)
        except SweepConfigError as e:
            rep.add(Finding("TW600", ERROR, f"pack entry {i}", str(e)))
            continue
        if cfg.run_id in seen:
            rep.add(Finding(
                "TW600", ERROR, f"pack entry {i}",
                f"duplicate run_id {cfg.run_id!r} — results are "
                "journaled per run_id, so ids must be unique"))
            continue
        seen.add(cfg.run_id)
        configs.append(cfg)
    if not data:
        rep.add(Finding("TW600", ERROR, "pack",
                        "a sweep pack needs at least one config"))
    if configs:
        rep.extend(lint_pack(SweepPack(tuple(configs)),
                             max_bucket=max_bucket))
    return len(data), rep


def lint_pack_path(path: str, *, max_bucket: int = 64,
                   speculate_default: Optional[str] = None
                   ) -> Tuple[int, LintReport]:
    """:func:`lint_pack_json` over a pack FILE (JSON or JSONL, the
    loader's dual grammar) — unreadable/undecodable files become
    TW600 findings, so ``lint-pack`` always produces a report."""
    rep = LintReport()
    try:
        with open(path) as f:
            text = f.read()
    except OSError as e:
        rep.add(Finding("TW600", ERROR, path,
                        f"pack file is unreadable: {e}"))
        return 0, rep
    try:
        data = json.loads(text)
    except json.JSONDecodeError as e:
        try:
            data = [json.loads(line) for line in text.splitlines()
                    if line.strip()]
        except json.JSONDecodeError:
            rep.add(Finding(
                "TW600", ERROR, path,
                f"pack file is neither a JSON list nor JSONL ({e})"))
            return 0, rep
    n, r = lint_pack_json(data, max_bucket=max_bucket,
                          speculate_default=speculate_default)
    return n, rep.extend(r)
