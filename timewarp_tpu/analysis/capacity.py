"""Static capacity proofs: worst-case mailbox fan-in vs ``mailbox_cap``.

Determinism contract #6 (core/scenario.py) makes overflow *counted and
dropped, never silent* — but a parity run must keep the counter at 0,
and a scenario whose topology makes overflow inevitable should be
rejected before any superstep runs, not discovered as a nonzero
``EngineState.overflow`` after a million-node run.

For ``static_dst`` scenarios the communication graph is fully known at
build time, so the worst case is computable exactly: the maximum
in-degree counted in outbox-slot edges is the number of messages that
can land co-temporally on one node in a single superstep wave (every
in-neighbor fires at the same instant and each declared slot sends).
A superstep delivers before it inserts, so ``mailbox_cap`` must absorb
at least one full wave; in-degree > cap is *provable* overflow —
an error. Dynamic-destination scenarios can't be proved either way
statically; they get the trivially sound ``n_nodes × max_out`` bound
reported (info) so the author sees what a flood could do.

``static_dst`` entries are also range-checked against ``[-1, n_nodes)``
(-1 = slot never used): an out-of-range declaration would make the
edge-engine topology inversion (edge_engine.py ``EdgeTopology.build``)
raise later with less context, and silently count as ``bad_dst`` on
the general engine.

**Fault-aware proofs (TW205/TW206).** The single-wave proof above is
about the fault-free graph; a fault schedule changes the worst case in
one way a static analysis can still bound: a ``degrade`` window whose
``scale`` stretches delays *widens the arrival spread* of the messages
sent inside it, so sends from several distinct supersteps of one
sender compress into one post-window arrival superstep — the deferred
deliveries "pile up". :func:`lint_capacity_faulted` recomputes the
worst-case co-temporal fan-in under the schedule: per degrade row the
number of send-supersteps whose messages can land inside one arrival
window of width ``W`` is ``1 + (degraded_spread - base_spread) // W``
(capped by how many supersteps the degrade window even contains),
applied per matching edge, with relief for senders provably dark for
the whole window (crashed, or partitioned away from the receiver) and
receivers provably down across the entire arrival span (down-node
deliveries are dropped, faults/apply.py). ``extra_us``-only rows and
``scale <= 1`` rows shift or shrink delays without widening the
spread — no pileup, no finding. The proof needs an upper delay bound;
link models without one (``FnDelay``) take the window-length cap.
"""

from __future__ import annotations

import math
from typing import Optional

import numpy as np

from ..core.scenario import Scenario
from .report import ERROR, INFO, Finding, LintReport

__all__ = ["lint_capacity", "worst_case_fan_in",
           "lint_capacity_faulted", "max_delay_us"]


def worst_case_fan_in(sc: Scenario):
    """``(fan_in, node)`` — the provable worst-case number of messages
    landing co-temporally on one node for a ``static_dst`` scenario, or
    ``(n_nodes * max_out, None)`` as the sound bound for dynamic
    destinations."""
    if sc.static_dst is None:
        return sc.n_nodes * sc.max_out, None
    sd = np.asarray(sc.static_dst)
    used = (sd >= 0) & (sd < sc.n_nodes)
    if not used.any():
        return 0, None
    deg = np.bincount(sd[used].astype(np.int64).ravel(),
                      minlength=sc.n_nodes)
    node = int(deg.argmax())
    return int(deg[node]), node


def lint_capacity(sc: Scenario) -> LintReport:
    rep = LintReport()
    name, K = sc.name, sc.mailbox_cap

    if sc.static_dst is None:
        bound = sc.n_nodes * sc.max_out
        rep.add(Finding(
            "TW203", INFO, name,
            f"dynamic destinations: worst-case co-temporal fan-in is "
            f"only boundable as n_nodes*max_out = {bound} "
            f"(mailbox_cap={K}); overflow is counted at run time "
            "(EngineState.overflow), not provable statically"))
        return rep

    sd = np.asarray(sc.static_dst)
    # shape is validated by Scenario.__post_init__; re-derive defensively
    # so a hand-built scenario bypassing the dataclass still lints
    if sd.shape != (sc.n_nodes, sc.max_out):
        rep.add(Finding(
            "TW201", ERROR, name,
            f"static_dst shape {sd.shape} != (n_nodes, max_out) = "
            f"({sc.n_nodes}, {sc.max_out})"))
        return rep

    bad = (sd < -1) | (sd >= sc.n_nodes)
    if bad.any():
        i, k = map(int, np.argwhere(bad)[0])
        rep.add(Finding(
            "TW201", ERROR, name,
            f"static_dst contains {int(bad.sum())} out-of-range "
            f"entr{'y' if bad.sum() == 1 else 'ies'} (first: "
            f"[{i}, {k}] = {int(sd[i, k])}); destinations must lie in "
            f"[-1, {sc.n_nodes}) with -1 = slot never used"))

    fan_in, node = worst_case_fan_in(sc)
    if fan_in > K:
        rep.add(Finding(
            "TW202", ERROR, name,
            f"provable mailbox overflow: node {node} has static "
            f"in-degree {fan_in} (outbox-slot edges) > "
            f"mailbox_cap={K}; one co-temporal firing wave of its "
            f"senders must drop {fan_in - K} message(s). Raise "
            f"mailbox_cap to >= {fan_in} or thin the topology"))
    else:
        rep.add(Finding(
            "TW204", INFO, name,
            f"static capacity proof: worst-case co-temporal fan-in "
            f"{fan_in} (node {node}) <= mailbox_cap={K}; a single "
            "superstep wave can never overflow"))
    return rep


# ---------------------------------------------------------------------------
# fault-aware proofs (TW205/TW206)
# ---------------------------------------------------------------------------

def max_delay_us(link) -> Optional[int]:
    """A static upper bound on ``link``'s sampled delay, or None when
    the model declares none (``FnDelay``, unknown classes). The dual of
    the declared ``min_delay_us``: heavy-tail models clamp at
    ``cap_us`` (net/delays.py), so every shipped model is bounded."""
    name = type(link).__name__
    if name == "FixedDelay":
        return int(link.delay)
    if name == "UniformDelay":
        return int(link.hi)
    if name in ("LogNormalDelay", "ParetoDelay"):
        return int(link.cap_us)
    if name == "SeededHashUniform":
        return int(link.hi_us)
    if name == "WithDrop":
        return max_delay_us(link.inner)
    if name == "Quantize":
        m = max_delay_us(link.inner)
        if m is None:
            return None
        q = int(link.quantum_us)
        # sample clamps to [min, cap] BEFORE rounding up to the grid
        return ((max(m, 1) + q - 1) // q) * q
    return None


def _window_fold(lw, base_min: int, base_max: Optional[int],
                 window: int) -> int:
    """How many distinct send-supersteps one degrade row can compress
    into a single arrival superstep (module docstring). 1 = no pileup
    beyond the fault-free single wave."""
    length = int(lw.t_end) - int(lw.t_start)
    if length <= 0:
        return 1                      # inert (padding) row
    W = max(1, int(window))
    # supersteps are at least W of virtual time apart (windowed
    # execution), so the degrade window spans at most this many
    # distinct send instants per sender
    sends_cap = max(1, math.ceil(length / W))
    if base_max is None:
        return sends_cap              # unbounded link: worst case
    d_min = max(1, (int(base_min) * lw._num) // lw._den + lw.extra_us)
    d_max = (int(base_max) * lw._num) // lw._den + lw.extra_us
    base_spread = max(0, int(base_max) - int(base_min))
    spread = max(0, d_max - d_min)
    # only the spread GROWTH vs the fault-free link compresses extra
    # send-supersteps into one arrival window (extra_us shifts, and
    # scale <= 1 shrinks — neither widens)
    return min(sends_cap, 1 + max(0, spread - base_spread) // W)


def _covering(events, t_lo: int, t_hi: int, lo, hi) -> set:
    """Node ids from ``events`` whose [lo(e), hi(e)) window covers the
    whole of ``[t_lo, t_hi)``."""
    return {e.node for e in events
            if lo(e) <= t_lo and hi(e) >= t_hi}


def lint_capacity_faulted(sc: Scenario, faults, link,
                          window: int, *,
                          subject: Optional[str] = None) -> LintReport:
    """Fault-aware static capacity proof (module docstring): prove
    ``mailbox_cap`` absorbs the worst-case co-temporal fan-in *under
    the fault schedule*, or name the violating degrade window and
    node (TW205 error / TW206 info proof). ``faults`` is a
    FaultSchedule or FaultFleet (every world's schedule is proved;
    the first violation is reported tagged with its world)."""
    rep = LintReport()
    name = subject or sc.name
    if sc.static_dst is None or faults is None:
        return rep                    # TW203 already reported the bound
    scheds = faults.schedules if hasattr(faults, "schedules") \
        else (faults,)
    K = sc.mailbox_cap
    sd = np.asarray(sc.static_dst)
    if sd.shape != (sc.n_nodes, sc.max_out):
        return rep                    # TW201 already errored
    used = (sd >= 0) & (sd < sc.n_nodes)
    if not used.any():
        return rep
    src_of = np.broadcast_to(
        np.arange(sc.n_nodes)[:, None], sd.shape)[used].ravel()
    dst_of = sd[used].astype(np.int64).ravel()
    base_deg = np.bincount(dst_of, minlength=sc.n_nodes)
    base_min = int(link.min_delay_us)
    base_max = max_delay_us(link)

    worst = (int(base_deg.max()), int(base_deg.argmax()), None, 1)
    violation = None
    windows = 0
    for b, sched in enumerate(scheds):
        tag = f"{name}[world {b}]" if len(scheds) > 1 else name
        for lw in sched.link_windows:
            if lw.t_end <= lw.t_start:
                continue
            windows += 1
            fold = _window_fold(lw, base_min, base_max, window)
            if fold <= 1:
                continue
            # the folded senders: matched by the row's src set, minus
            # senders provably dark for the WHOLE window (crashed, or
            # partitioned away from every receiver — handled per-edge
            # below for partitions)
            dark = _covering(sched.crashes, lw.t_start, lw.t_end,
                             lambda c: c.t_down, lambda c: c.t_up)
            in_src = np.ones(sc.n_nodes, bool) if lw.src is None \
                else np.isin(np.arange(sc.n_nodes), list(lw.src))
            if dark:
                in_src &= ~np.isin(np.arange(sc.n_nodes), list(dark))
            edge_fold = in_src[src_of]
            if lw.dst is not None:
                edge_fold &= np.isin(dst_of, list(lw.dst))
            # partition relief: an edge cut for the whole degrade
            # window sends nothing across it during the window
            for part in sched.partitions:
                if part.t_start <= lw.t_start \
                        and part.t_end >= lw.t_end:
                    gid = np.full(sc.n_nodes, -1)
                    for gi, g in enumerate(part.groups):
                        for i in g:
                            if i < sc.n_nodes:
                                gid[i] = gi
                    cut = (gid[src_of] >= 0) & (gid[dst_of] >= 0) \
                        & (gid[src_of] != gid[dst_of])
                    edge_fold &= ~cut
            deg = base_deg + np.bincount(
                dst_of[edge_fold], minlength=sc.n_nodes) * (fold - 1)
            # receiver relief: a node down across the entire arrival
            # span never enqueues these deliveries (down-node drops
            # are counted as fault_dropped, faults/apply.py)
            if base_max is not None:
                d_min = max(1, (base_min * lw._num) // lw._den
                            + lw.extra_us)
                d_max = (base_max * lw._num) // lw._den + lw.extra_us
                down = _covering(sched.crashes,
                                 lw.t_start + d_min, lw.t_end + d_max,
                                 lambda c: c.t_down, lambda c: c.t_up)
                for r in down:
                    if r < sc.n_nodes:
                        deg[r] = 0
            node = int(deg.argmax())
            fan = int(deg[node])
            if fan > worst[0]:
                worst = (fan, node, lw, fold)
            if fan > K and violation is None:
                violation = (tag, fan, node, lw, fold)
    if violation is not None:
        tag, fan, node, lw, fold = violation
        rep.add(Finding(
            "TW205", ERROR, tag,
            f"provable mailbox overflow under the fault schedule: "
            f"degrade window [{lw.t_start}, {lw.t_end}) (scale "
            f"{lw.scale}, +{lw.extra_us}us) defers deliveries from "
            f"up to {fold} send-supersteps into one post-window "
            f"arrival wave — node {node} takes worst-case fan-in "
            f"{fan} > mailbox_cap={K}. Raise mailbox_cap to >= {fan}, "
            "shorten/weaken the degrade window, or widen the window "
            "so fewer send instants fit inside it"))
    elif windows:
        fan, node, lw, fold = worst
        at = "" if lw is None else (
            f" (tightest: degrade [{lw.t_start}, {lw.t_end}) folding "
            f"{fold} send-supersteps onto node {node})")
        rep.add(Finding(
            "TW206", INFO, name,
            f"fault-aware capacity proof: worst-case co-temporal "
            f"fan-in stays {fan} <= mailbox_cap={K} under all "
            f"{windows} degrade window(s){at}; restarts purge and "
            "partitions only cut — neither grows a wave"))
    return rep
