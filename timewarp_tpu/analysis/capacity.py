"""Static capacity proofs: worst-case mailbox fan-in vs ``mailbox_cap``.

Determinism contract #6 (core/scenario.py) makes overflow *counted and
dropped, never silent* — but a parity run must keep the counter at 0,
and a scenario whose topology makes overflow inevitable should be
rejected before any superstep runs, not discovered as a nonzero
``EngineState.overflow`` after a million-node run.

For ``static_dst`` scenarios the communication graph is fully known at
build time, so the worst case is computable exactly: the maximum
in-degree counted in outbox-slot edges is the number of messages that
can land co-temporally on one node in a single superstep wave (every
in-neighbor fires at the same instant and each declared slot sends).
A superstep delivers before it inserts, so ``mailbox_cap`` must absorb
at least one full wave; in-degree > cap is *provable* overflow —
an error. Dynamic-destination scenarios can't be proved either way
statically; they get the trivially sound ``n_nodes × max_out`` bound
reported (info) so the author sees what a flood could do.

``static_dst`` entries are also range-checked against ``[-1, n_nodes)``
(-1 = slot never used): an out-of-range declaration would make the
edge-engine topology inversion (edge_engine.py ``EdgeTopology.build``)
raise later with less context, and silently count as ``bad_dst`` on
the general engine.
"""

from __future__ import annotations

import numpy as np

from ..core.scenario import Scenario
from .report import ERROR, INFO, Finding, LintReport

__all__ = ["lint_capacity", "worst_case_fan_in"]


def worst_case_fan_in(sc: Scenario):
    """``(fan_in, node)`` — the provable worst-case number of messages
    landing co-temporally on one node for a ``static_dst`` scenario, or
    ``(n_nodes * max_out, None)`` as the sound bound for dynamic
    destinations."""
    if sc.static_dst is None:
        return sc.n_nodes * sc.max_out, None
    sd = np.asarray(sc.static_dst)
    used = (sd >= 0) & (sd < sc.n_nodes)
    if not used.any():
        return 0, None
    deg = np.bincount(sd[used].astype(np.int64).ravel(),
                      minlength=sc.n_nodes)
    node = int(deg.argmax())
    return int(deg[node]), node


def lint_capacity(sc: Scenario) -> LintReport:
    rep = LintReport()
    name, K = sc.name, sc.mailbox_cap

    if sc.static_dst is None:
        bound = sc.n_nodes * sc.max_out
        rep.add(Finding(
            "TW203", INFO, name,
            f"dynamic destinations: worst-case co-temporal fan-in is "
            f"only boundable as n_nodes*max_out = {bound} "
            f"(mailbox_cap={K}); overflow is counted at run time "
            "(EngineState.overflow), not provable statically"))
        return rep

    sd = np.asarray(sc.static_dst)
    # shape is validated by Scenario.__post_init__; re-derive defensively
    # so a hand-built scenario bypassing the dataclass still lints
    if sd.shape != (sc.n_nodes, sc.max_out):
        rep.add(Finding(
            "TW201", ERROR, name,
            f"static_dst shape {sd.shape} != (n_nodes, max_out) = "
            f"({sc.n_nodes}, {sc.max_out})"))
        return rep

    bad = (sd < -1) | (sd >= sc.n_nodes)
    if bad.any():
        i, k = map(int, np.argwhere(bad)[0])
        rep.add(Finding(
            "TW201", ERROR, name,
            f"static_dst contains {int(bad.sum())} out-of-range "
            f"entr{'y' if bad.sum() == 1 else 'ies'} (first: "
            f"[{i}, {k}] = {int(sd[i, k])}); destinations must lie in "
            f"[-1, {sc.n_nodes}) with -1 = slot never used"))

    fan_in, node = worst_case_fan_in(sc)
    if fan_in > K:
        rep.add(Finding(
            "TW202", ERROR, name,
            f"provable mailbox overflow: node {node} has static "
            f"in-degree {fan_in} (outbox-slot edges) > "
            f"mailbox_cap={K}; one co-temporal firing wave of its "
            f"senders must drop {fan_in - K} message(s). Raise "
            f"mailbox_cap to >= {fan_in} or thin the topology"))
    else:
        rep.add(Finding(
            "TW204", INFO, name,
            f"static capacity proof: worst-case co-temporal fan-in "
            f"{fan_in} (node {node}) <= mailbox_cap={K}; a single "
            "superstep wave can never overflow"))
    return rep
