"""Jaxpr contract lints: check a scenario's step function *before* any
engine run.

The framework's determinism contract (core/scenario.py:28-47) is only
usable at scale if violations are caught statically — a host callback
or an int32 time truncation inside a user step function otherwise
surfaces as a parity digest mismatch thousands of supersteps into a
million-node run. This module traces ``Scenario.step`` abstractly with
``jax.make_jaxpr`` under the exact aval conventions the engines use
(inbox width ``mailbox_cap``, int64 ``now``, the threefry entropy pair
from core/rng.py) and checks:

- **TW101** host-escape primitives (``pure_callback`` / ``io_callback``
  / ``debug_callback`` …): arbitrary host IO has no deterministic
  virtual-time meaning (the same reason the pure emulator rejects
  ``AwaitIO``, interp/ref/des.py) and breaks oracle/engine parity.
- **TW102/TW103** time-dtype discipline: int64 time values (``now``,
  ``inbox.time``, int64 state leaves) must never be truncated to a
  narrower integer (TW102) or promoted to float (TW103) — found by
  taint-propagating through the jaxpr, including into
  scan/while/cond/pjit sub-jaxprs.
- **TW104** ``next_wake`` must be a scalar int64 (the engine compares
  it against ``NEVER = 2^62-1``, which no narrower dtype can hold).
- **TW105** outbox conformance: ``valid`` bool[max_out], ``dst``
  integer[max_out], ``payload`` int32[max_out, payload_width] — the
  shapes/dtypes the routing sorts and mailbox scatters are compiled
  for.
- **TW106** state pytree stability: ``step`` must return states with
  the structure/shape/dtype it was given (``lax.scan`` carries them).
- **TW107–TW110** declared-flag dataflow: ``needs_key=False`` ⇔ the
  key input has no consumers in the jaxpr, ``inbox_src=False`` ⇔
  ``inbox.src`` is unused. A false ``False`` is an error (the engine
  feeds ``None``/zeros — silent divergence); a conservative ``True``
  over an unused input is a perf warning (the engine derives entropy /
  scatters the src plane for nothing every superstep).

All checks are abstract — nothing is executed, so ``lint="warn"``
engine construction cannot change run behavior.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional, Set, Tuple

from ..utils import jaxconfig  # noqa: F401  (must precede jax use)

import jax
import jax.numpy as jnp

try:
    # the version-stable home of the jaxpr IR types (jax >= 0.6
    # removed them from jax.core; jax.extend.core carries them on both
    # sides of that line — same shim idea as parallel/mesh.py)
    from jax.extend import core as jcore
    _ = jcore.Var, jcore.Literal, jcore.Jaxpr, jcore.ClosedJaxpr
except (ImportError, AttributeError):  # pragma: no cover — old jax
    from jax import core as jcore

from ..core.scenario import Inbox, Scenario
from .report import ERROR, INFO, WARNING, Finding, LintReport

__all__ = ["lint_step_jaxpr", "HOST_ESCAPE_PRIMITIVES"]

#: primitives whose presence in a step jaxpr breaks the determinism
#: contract (host escapes have no virtual-time meaning)
HOST_ESCAPE_PRIMITIVES = frozenset({
    "pure_callback", "io_callback", "debug_callback", "callback",
    "outside_call", "host_callback_call", "infeed", "outfeed",
})

_I64 = (jnp.dtype(jnp.int64), jnp.dtype(jnp.uint64))


def _is_time_dtype(dt) -> bool:
    return jnp.dtype(dt) in _I64


def _is_time_var(v) -> bool:
    """64-bit-integer-typed var (False for drop vars / tokens — no
    isinstance on DropVar, which has no version-stable public home)."""
    dt = getattr(getattr(v, "aval", None), "dtype", None)
    return dt is not None and _is_time_dtype(dt)


# ----------------------------------------------------------------------
# higher-order eqn plumbing
# ----------------------------------------------------------------------

def _open(j):
    """ClosedJaxpr -> Jaxpr (identity for open jaxprs)."""
    return j.jaxpr if isinstance(j, jcore.ClosedJaxpr) else j


def _subjaxpr_maps(eqn) -> Optional[List[Tuple[
        Any, List[Optional[int]], List[Optional[int]],
        List[Optional[int]]]]]:
    """For a higher-order eqn, return ``[(jaxpr, invar_map, outvar_map,
    carry_map), ...]`` where ``invar_map[i]`` is the index into
    ``eqn.invars`` that feeds the sub-jaxpr's i-th invar (None = no
    direct feed), ``outvar_map[o]`` the index into ``eqn.outvars`` the
    o-th sub outvar produces, and ``carry_map[o]`` the sub-jaxpr
    *invar* index the o-th sub outvar loops back into (scan/while
    carries; None = no loop). Returns None for first-order eqns; ``[]``
    for an *unknown* higher-order primitive (callers must be
    conservative).
    """
    name = eqn.primitive.name
    params = eqn.params
    if name in ("pjit", "closed_call", "core_call", "remat", "remat2",
                "checkpoint", "custom_jvp_call", "custom_vjp_call"):
        j = params.get("jaxpr") or params.get("call_jaxpr")
        if j is None:
            return []
        jx = _open(j)
        return [(jx, list(range(len(jx.invars))),
                 list(range(len(jx.outvars))),
                 [None] * len(jx.outvars))]
    if name == "scan":
        jx = _open(params["jaxpr"])
        nc, ncar = params["num_consts"], params["num_carry"]
        # eqn.invars = consts + carry_init + xs; body invars align 1:1
        # (xs enter as per-iteration slices — same positions). Body
        # outvars = carry + ys align 1:1 with eqn.outvars; carry outvar
        # o feeds body invar nc + o on the next iteration.
        return [(jx, list(range(len(jx.invars))),
                 list(range(len(jx.outvars))),
                 [nc + o if o < ncar else None
                  for o in range(len(jx.outvars))])]
    if name == "while":
        cj, bj = _open(params["cond_jaxpr"]), _open(params["body_jaxpr"])
        cn, bn = params["cond_nconsts"], params["body_nconsts"]
        cmap = [i if i < cn else cn + bn + (i - cn)
                for i in range(len(cj.invars))]
        bmap = [cn + i if i < bn else cn + bn + (i - bn)
                for i in range(len(bj.invars))]
        # body outvars are the carry, which is eqn.outvars 1:1 and
        # loops back into body invar bn + o; the cond jaxpr produces
        # only the predicate
        return [(cj, cmap, [None] * len(cj.outvars),
                 [None] * len(cj.outvars)),
                (bj, bmap, list(range(len(bj.outvars))),
                 [bn + o for o in range(len(bj.outvars))])]
    if name == "cond":
        out = []
        for br in params["branches"]:
            jx = _open(br)
            out.append((jx, [1 + i for i in range(len(jx.invars))],
                        list(range(len(jx.outvars))),
                        [None] * len(jx.outvars)))
        return out
    # first-order unless the params hide a jaxpr we don't know how to map
    for v in params.values():
        if isinstance(v, (jcore.Jaxpr, jcore.ClosedJaxpr)):
            return []
        if isinstance(v, (tuple, list)) and any(
                isinstance(x, (jcore.Jaxpr, jcore.ClosedJaxpr)) for x in v):
            return []
    return None


def _all_jaxprs(jaxpr):
    """Every jaxpr reachable from ``jaxpr`` (itself included)."""
    yield jaxpr
    for eqn in jaxpr.eqns:
        for v in eqn.params.values():
            vs = v if isinstance(v, (tuple, list)) else (v,)
            for x in vs:
                if isinstance(x, (jcore.Jaxpr, jcore.ClosedJaxpr)):
                    yield from _all_jaxprs(_open(x))


# ----------------------------------------------------------------------
# invar consumption (flag dataflow)
# ----------------------------------------------------------------------

def _used_invar_positions(jaxpr, cache: Dict[int, Set[Any]]) -> Set[Any]:
    """The set of ``jaxpr`` vars that are *actually consumed* — fed to a
    first-order eqn, or fed to a sub-jaxpr invar that is itself
    consumed (so dead pass-through plumbing does not count as use)."""
    key = id(jaxpr)
    if key in cache:
        return cache[key]
    used: Set[Any] = set()
    cache[key] = used           # cycle guard (jaxprs are acyclic, but
    # a var flowing straight to an output IS consumed — a step that
    # returns its key (or inbox.src) in state observes it, and the
    # engine would feed None/zeros for the conservative flag
    used.update(v for v in jaxpr.outvars if isinstance(v, jcore.Var))
    for eqn in jaxpr.eqns:      # the cache doubles as memo
        maps = _subjaxpr_maps(eqn)
        if maps is None or maps == []:
            # first-order or unknown higher-order: every invar counts
            for v in eqn.invars:
                if isinstance(v, jcore.Var):
                    used.add(v)
            continue
        live_positions: Set[int] = set()
        for jx, invmap, _, _ in maps:
            inner_used = _used_invar_positions(jx, cache)
            for i, pos in enumerate(invmap):
                if pos is not None and jx.invars[i] in inner_used:
                    live_positions.add(pos)
        for pos in live_positions:
            v = eqn.invars[pos]
            if isinstance(v, jcore.Var):
                used.add(v)
    return used


# ----------------------------------------------------------------------
# time-dtype taint
# ----------------------------------------------------------------------

def _taint_jaxpr(jaxpr, tainted: Set[Any], emit) -> None:
    """Propagate int64-time taint through ``jaxpr`` eqns in order,
    calling ``emit(kind, eqn)`` on a truncating or float-promoting
    ``convert_element_type`` of a tainted value. Taint survives any
    first-order op whose output stays 64-bit integer; comparisons
    (bool) and legitimate narrow results drop it."""
    for eqn in jaxpr.eqns:
        tin = any(isinstance(v, jcore.Var) and v in tainted
                  for v in eqn.invars)
        if not tin:
            continue
        name = eqn.primitive.name
        if name == "convert_element_type":
            src = eqn.invars[0]
            new = jnp.dtype(eqn.params["new_dtype"])
            if isinstance(src, jcore.Var) and src in tainted:
                if jnp.issubdtype(new, jnp.floating):
                    emit("float", eqn)
                elif (jnp.issubdtype(new, jnp.integer)
                        and new.itemsize < 8):
                    emit("truncate", eqn)
        maps = _subjaxpr_maps(eqn)
        if maps:
            # seed inner taint from the mapped outer invars; iterate to
            # a fixpoint so loop-carried taint (scan/while carries)
            # propagates — bounded tiny (taint sets only grow)
            out_tainted: Set[int] = set()
            for jx, invmap, outmap, carrymap in maps:
                inner: Set[Any] = set()
                for i, pos in enumerate(invmap):
                    if pos is None:
                        continue
                    v = eqn.invars[pos]
                    if isinstance(v, jcore.Var) and v in tainted:
                        inner.add(jx.invars[i])
                while True:
                    before = len(inner)
                    _taint_jaxpr(jx, inner, emit)
                    for o, ov in enumerate(jx.outvars):
                        if isinstance(ov, jcore.Var) and ov in inner:
                            if outmap[o] is not None:
                                out_tainted.add(outmap[o])
                            # loop-carried taint: a tainted carry
                            # outvar re-enters at its carry invar
                            if carrymap[o] is not None:
                                inner.add(jx.invars[carrymap[o]])
                    if len(inner) == before:
                        break
            for pos in out_tainted:
                ov = eqn.outvars[pos]
                if _is_time_var(ov):
                    tainted.add(ov)
            continue
        # first-order (or unknown higher-order) default: 64-bit integer
        # outputs of a tainted computation stay tainted
        for ov in eqn.outvars:
            if _is_time_var(ov):
                tainted.add(ov)


# ----------------------------------------------------------------------
# entry point
# ----------------------------------------------------------------------

def _lint_avals(sc: Scenario):
    """The engines' aval conventions for one (vmapped-out) node."""
    K, P = sc.mailbox_cap, sc.payload_width
    state0, _ = sc.init(0)
    state0 = jax.tree.map(jnp.asarray, state0)
    inbox = Inbox(valid=jnp.zeros((K,), bool),
                  src=jnp.zeros((K,), jnp.int32),
                  time=jnp.zeros((K,), jnp.int64),
                  payload=jnp.zeros((K, P), jnp.int32))
    now = jnp.int64(0)
    nid = jnp.int32(0)
    key = (jnp.zeros((), jnp.uint32), jnp.zeros((), jnp.uint32))
    return state0, inbox, now, nid, key


def lint_step_jaxpr(sc: Scenario) -> LintReport:
    """Trace ``sc.step`` abstractly and run every jaxpr contract lint.
    Never executes the step; never raises on untraceable steps (the
    engine's own trace produces the authoritative error — TW100 marks
    the lint as unable to look inside)."""
    rep = LintReport()
    name = sc.name
    M, P = sc.max_out, sc.payload_width

    try:
        state0, inbox, now, nid, key = _lint_avals(sc)
    except Exception as e:  # noqa: BLE001 — lint must not crash callers
        rep.add(Finding("TW100", WARNING, name,
                        f"init(0) failed under lint ({e!r}); jaxpr "
                        "lints skipped"))
        return rep

    key_traced = True
    try:
        closed, out_shape = jax.make_jaxpr(sc.step, return_shape=True)(
            state0, inbox, now, nid, key)
    except Exception as e_with_key:  # noqa: BLE001
        if sc.needs_key:
            rep.add(Finding("TW100", WARNING, name,
                            "step is not traceable under the engine "
                            f"aval conventions ({e_with_key!r}); jaxpr "
                            "lints skipped"))
            return rep
        # needs_key=False engines pass key=None — some steps require it
        key, key_traced = None, False
        try:
            closed, out_shape = jax.make_jaxpr(
                sc.step, return_shape=True)(state0, inbox, now, nid, key)
        except Exception as e:  # noqa: BLE001
            rep.add(Finding("TW100", WARNING, name,
                            "step is not traceable under the engine "
                            f"aval conventions ({e!r}); jaxpr lints "
                            "skipped"))
            return rep

    jaxpr = closed.jaxpr

    # -- TW101: host-escape primitives ---------------------------------
    seen_escapes = []
    for jx in _all_jaxprs(jaxpr):
        for eqn in jx.eqns:
            if eqn.primitive.name in HOST_ESCAPE_PRIMITIVES:
                seen_escapes.append(eqn.primitive.name)
    for prim in sorted(set(seen_escapes)):
        rep.add(Finding(
            "TW101", ERROR, name,
            f"step contains host-escape primitive {prim!r} "
            f"(x{seen_escapes.count(prim)}): host callbacks have no "
            "deterministic virtual-time meaning and break oracle/"
            "engine parity — compute inside the step or precompute "
            "into state"))

    # -- invar layout ----------------------------------------------------
    state_leaves = jax.tree.flatten(state0)[0]
    ns = len(state_leaves)
    iv = jaxpr.invars
    # flatten order: state leaves, inbox(valid, src, time, payload),
    # now, node_id, key words
    v_src, v_time, v_now = iv[ns + 1], iv[ns + 2], iv[ns + 4]
    key_vars = list(iv[ns + 6:ns + 8]) if key_traced else []

    # -- TW107..TW110: declared-flag dataflow ----------------------------
    used = _used_invar_positions(jaxpr, {})
    if key_traced:
        key_used = any(v in used for v in key_vars)
        if key_used and not sc.needs_key:
            rep.add(Finding(
                "TW107", ERROR, name,
                "needs_key=False but the step consumes its key input; "
                "engines pass key=None for this flag, so the run would "
                "crash at trace time (or silently use garbage). Declare "
                "needs_key=True"))
        elif not key_used and sc.needs_key:
            rep.add(Finding(
                "TW108", WARNING, name,
                "needs_key=True but the key input has no consumers in "
                "the jaxpr: engines derive per-(node, instant) threefry "
                "entropy every superstep for nothing. Declare "
                "needs_key=False"))
    src_used = v_src in used
    if src_used and not sc.inbox_src:
        rep.add(Finding(
            "TW109", ERROR, name,
            "inbox_src=False but the step reads inbox.src; engines "
            "elide the src mailbox plane for this flag and present "
            "zeros — sender identity would silently diverge between "
            "interpreters. Declare inbox_src=True"))
    elif not src_used and sc.inbox_src:
        rep.add(Finding(
            "TW110", WARNING, name,
            "inbox.src has no consumers in the jaxpr but "
            "inbox_src=True: the engines scatter the mailbox src plane "
            "(~1/3 of the dense random-delivery cost floor, "
            "PERF_r04.md) for a field the step never reads. Declare "
            "inbox_src=False"))

    # -- TW102/TW103: time-dtype taint ----------------------------------
    tainted: Set[Any] = {v_now, v_time}
    for i, leaf in enumerate(state_leaves):
        if _is_time_dtype(jnp.asarray(leaf).dtype):
            tainted.add(iv[i])
    # dedupe by eqn identity: the loop-carry fixpoint re-walks bodies
    hit_ids: Dict[str, Set[int]] = {"truncate": set(), "float": set()}

    def emit(kind, eqn):
        hit_ids[kind].add(id(eqn))

    _taint_jaxpr(jaxpr, tainted, emit)
    hits = {k: len(v) for k, v in hit_ids.items()}
    if hits["truncate"]:
        rep.add(Finding(
            "TW102", ERROR, name,
            f"int64 time value truncated to a narrower integer dtype "
            f"({hits['truncate']} conversion(s) in the step jaxpr): "
            "virtual time exceeds int32 after ~35 minutes; keep "
            "next_wake/inbox.time arithmetic in int64"))
    if hits["float"]:
        rep.add(Finding(
            "TW103", ERROR, name,
            f"int64 time value promoted to float "
            f"({hits['float']} conversion(s) in the step jaxpr): float "
            "time breaks the bit-exact cross-backend contract "
            "(core/time.py — int64 µs only). Check for python-float "
            "literals leaking into time arithmetic"))

    # -- output conformance ---------------------------------------------
    try:
        state_out, out, wake = out_shape
    except (TypeError, ValueError):
        rep.add(Finding(
            "TW105", ERROR, name,
            "step must return (state', outbox, next_wake); got "
            f"{jax.tree.structure(out_shape)}"))
        return rep

    # TW104: next_wake scalar int64
    wake_dt, wake_shape = jnp.dtype(wake.dtype), tuple(wake.shape)
    if wake_shape != () or wake_dt != jnp.dtype(jnp.int64):
        rep.add(Finding(
            "TW104", ERROR, name,
            f"next_wake must be a scalar int64 (got shape {wake_shape}, "
            f"dtype {wake_dt}): the engine clamps it against NEVER = "
            "2^62-1, which no narrower dtype can represent"))

    # TW105: outbox conformance
    ob = None
    if not (hasattr(out, "valid") and hasattr(out, "dst")
            and hasattr(out, "payload")):
        rep.add(Finding(
            "TW105", ERROR, name,
            "second return value must be an Outbox(valid, dst, "
            f"payload); got {type(out).__name__}"))
    else:
        ob = out
    if ob is not None:
        checks = [
            ("valid", ob.valid, (M,), (jnp.dtype(bool),)),
            ("dst", ob.dst, (M,),
             tuple(jnp.dtype(d) for d in (jnp.int32, jnp.int64,
                                          jnp.int16, jnp.int8))),
            ("payload", ob.payload, (M, P), (jnp.dtype(jnp.int32),)),
        ]
        for fname, leaf, want_shape, want_dts in checks:
            shape, dt = tuple(leaf.shape), jnp.dtype(leaf.dtype)
            if shape != want_shape:
                rep.add(Finding(
                    "TW105", ERROR, name,
                    f"outbox.{fname} shape {shape} != {want_shape} "
                    f"(max_out={M}, payload_width={P}): the routing "
                    "sorts and mailbox scatters are compiled for the "
                    "declared widths"))
            elif dt not in want_dts:
                rep.add(Finding(
                    "TW105", ERROR, name,
                    f"outbox.{fname} dtype {dt} is not "
                    f"{'/'.join(str(d) for d in want_dts)}: engines "
                    "scatter payloads into int32 mailbox planes and "
                    "read dst as an integer index"))
            elif fname == "dst" and dt != jnp.dtype(jnp.int32):
                rep.add(Finding(
                    "TW105", INFO, name,
                    f"outbox.dst dtype {dt}; engines convert to int32 "
                    "every superstep — emit int32 directly"))

    # TW106: state pytree stability
    in_td = jax.tree.structure(state0)
    out_td = jax.tree.structure(state_out)
    if in_td != out_td:
        rep.add(Finding(
            "TW106", ERROR, name,
            f"state pytree structure changes across step ({in_td} -> "
            f"{out_td}); lax.scan carries the state and requires a "
            "stable structure"))
    else:
        for i, (a, b) in enumerate(zip(state_leaves,
                                       jax.tree.flatten(state_out)[0])):
            a = jnp.asarray(a)
            if tuple(a.shape) != tuple(b.shape) \
                    or jnp.dtype(a.dtype) != jnp.dtype(b.dtype):
                rep.add(Finding(
                    "TW106", ERROR, name,
                    f"state leaf #{i} changes shape/dtype across step "
                    f"({a.shape}/{a.dtype} -> {b.shape}/{b.dtype}); "
                    "lax.scan requires shape/dtype-stable carries"))
    return rep
