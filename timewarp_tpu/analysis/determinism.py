"""Determinism sanitizer (TW7xx): jaxpr-level bit-exactness threats.

The whole framework rests on runs being bit-identical across engines,
batching, checkpoints, and backends (core/scenario.py determinism
contract; every law test compares sha256 digests). Four primitive
families are known to break that silently — the run *works*, the
digests just differ between platforms or executions:

- **TW701** (error) — unordered float reductions: a float scatter-add
  with duplicate indices (accumulation order is
  implementation-defined) and float cross-device ``psum`` (reduction
  tree order varies with topology). Integer scatter-adds are exact
  and commutative — only floating accumulation is flagged.
- **TW702** (warning) — platform-dependent transcendentals (exp, log,
  tanh, erf, pow, ...): each backend's libm differs in the last ulp,
  so float transcendentals are deterministic per-platform but not
  bit-stable ACROSS platforms. Warning, not error: the shipped
  heavy-tail link samplers (lognormal/pareto, net/delays.py) use them
  deliberately and re-quantize to int64 µs — the documented way to
  keep digests exact is exactly that, quantize before the result
  re-enters int64 time.
- **TW703** (error) — non-threefry randomness: ``rng_bit_generator``
  (the XLA-native generator, backend-dependent streams), the legacy
  ``rng_uniform``, and any typed-key ``random_*`` primitive consuming
  a non-``fry`` key (``key<rbg>``/``key<urbg>`` — the impl rides the
  key dtype). The framework's entropy is counter-based
  threefry2x32 (core/rng.py) precisely so streams are
  backend-invariant; any other generator silently forks the contract.
- **TW704** (error) — host callbacks reachable from *traced engine
  code* (same primitive set as the step-level TW101, jaxpr_lint.py):
  a callback inside the lowered driver escapes virtual time entirely.

Two scan surfaces share the checks: :func:`lint_step_determinism`
scans a scenario's step jaxpr (runs inside ``lint_scenario``, so
every engine construction and ``timewarp-tpu lint`` get it; TW101
already covers host escapes there), and :func:`lint_engine_jaxpr`
scans a built engine's lowered ``_step_all`` driver — everything the
engine adds around the step: routing sorts, mailbox scatters, fault
masks, telemetry/record/verify/speculation planes.

:func:`prove_mode_neutrality` (TW705) generically re-proves the
off-mode jaxpr-neutrality pins: for every observability/execution
knob (telemetry, record, verify, speculate), an engine built with the
knob explicitly ``"off"`` must lower to the byte-identical driver
jaxpr of the baseline engine — the zero-overhead-off contract that
was previously one hand-written pin per knob
(tests/test_zztelemetry.py and siblings keep the named instances;
this proves the family). ``timewarp-tpu lint --jaxpr`` runs both
scans over every shipped engine x mode (cli.py ``jaxpr_sweep``).
"""

from __future__ import annotations

from typing import Optional, Tuple

from ..utils import jaxconfig  # noqa: F401  (must precede jax use)

import jax
import jax.numpy as jnp

from ..core.scenario import Scenario
from .jaxpr_lint import (HOST_ESCAPE_PRIMITIVES, _all_jaxprs,
                         _lint_avals)
from .report import ERROR, INFO, WARNING, Finding, LintReport

__all__ = ["lint_step_determinism", "lint_engine_jaxpr",
           "prove_mode_neutrality", "scan_jaxpr_determinism",
           "UNORDERED_FLOAT_REDUCTIONS", "TRANSCENDENTALS",
           "NON_THREEFRY_RNG"]

#: primitives whose float accumulation order is implementation-defined
UNORDERED_FLOAT_REDUCTIONS = frozenset({
    "scatter-add", "scatter-mul", "psum"})

#: libm-backed primitives whose last-ulp behavior differs per backend
TRANSCENDENTALS = frozenset({
    "exp", "exp2", "expm1", "log", "log2", "log1p", "logistic",
    "sin", "cos", "tan", "asin", "acos", "atan", "atan2",
    "sinh", "cosh", "tanh", "asinh", "acosh", "atanh",
    "erf", "erfc", "erf_inv", "lgamma", "digamma", "pow", "cbrt",
})

#: random primitives that are NOT counter-based threefry
NON_THREEFRY_RNG = frozenset({"rng_bit_generator", "rng_uniform"})


def _is_float(v) -> bool:
    dt = getattr(getattr(v, "aval", None), "dtype", None)
    return dt is not None and jnp.issubdtype(jnp.dtype(dt),
                                             jnp.floating)


def _key_impl(v) -> Optional[str]:
    """The PRNG impl of a typed-key operand (``key<fry>`` /
    ``key<rbg>`` / ...), or None for non-key avals. The typed-key
    ``random_*`` primitives carry their generator in the key DTYPE,
    not the primitive name."""
    dt = getattr(getattr(v, "aval", None), "dtype", None)
    s = str(dt) if dt is not None else ""
    if s.startswith("key<") and s.endswith(">"):
        return s[4:-1]
    return None


def scan_jaxpr_determinism(jaxpr, subject: str, *,
                           host_escapes: bool = True) -> LintReport:
    """Scan one (open) jaxpr — sub-jaxprs included — for the TW7xx
    primitive families. ``host_escapes=False`` skips TW704 (the
    step-level caller already reports TW101 for the same eqns)."""
    rep = LintReport()
    unordered, transcend, rng, escapes = {}, {}, {}, {}
    for jx in _all_jaxprs(jaxpr):
        for eqn in jx.eqns:
            name = eqn.primitive.name
            if name in UNORDERED_FLOAT_REDUCTIONS and (
                    any(_is_float(v) for v in eqn.outvars)
                    or any(_is_float(v) for v in eqn.invars)):
                unordered[name] = unordered.get(name, 0) + 1
            elif name in TRANSCENDENTALS and (
                    any(_is_float(v) for v in eqn.outvars)):
                transcend[name] = transcend.get(name, 0) + 1
            elif name in NON_THREEFRY_RNG:
                rng[name] = rng.get(name, 0) + 1
            elif name.startswith("random_"):
                # typed-key primitives: the generator is the key's
                # DTYPE (key<fry> = threefry, key<rbg>/key<urbg> =
                # the XLA-native backend-dependent generator)
                impl = next((im for im in map(_key_impl, eqn.invars)
                             if im is not None and im != "fry"), None)
                if impl is not None:
                    k = f"{name}[{impl}]"
                    rng[k] = rng.get(k, 0) + 1
            elif host_escapes and name in HOST_ESCAPE_PRIMITIVES:
                escapes[name] = escapes.get(name, 0) + 1
    for name, n in sorted(unordered.items()):
        rep.add(Finding(
            "TW701", ERROR, subject,
            f"unordered float reduction {name!r} (x{n}): float "
            "accumulation order is implementation-defined, so "
            "duplicate-index scatters / cross-device sums produce "
            "different bits per backend and break every digest law. "
            "Accumulate in integers (fixed-point) or pre-sort a "
            "unique-index scatter"))
    for name, n in sorted(transcend.items()):
        rep.add(Finding(
            "TW702", WARNING, subject,
            f"platform-dependent transcendental {name!r} (x{n}): "
            "libm results differ in the last ulp across backends — "
            "deterministic per platform, not bit-stable across them. "
            "Quantize the result to integer µs before it re-enters "
            "virtual time (the shipped heavy-tail samplers' "
            "discipline, net/delays.py)"))
    for name, n in sorted(rng.items()):
        rep.add(Finding(
            "TW703", ERROR, subject,
            f"non-threefry randomness {name!r} (x{n}): its stream is "
            "backend-dependent; the framework's entropy is "
            "counter-based threefry2x32 (core/rng.py) so every "
            "backend draws identical words — use jax.random with the "
            "engine-provided key"))
    for name, n in sorted(escapes.items()):
        rep.add(Finding(
            "TW704", ERROR, subject,
            f"host callback {name!r} (x{n}) reachable from traced "
            "engine code: a callback has no deterministic "
            "virtual-time meaning and escapes the replay/digest "
            "contract entirely"))
    return rep


def lint_step_determinism(sc: Scenario) -> LintReport:
    """TW701-703 over a scenario's step jaxpr (TW101 owns host
    escapes at this level). Traces under the engines' aval
    conventions; untraceable steps are skipped silently — TW100
    (jaxpr_lint.py) already reports the trace failure."""
    try:
        state0, inbox, now, nid, key = _lint_avals(sc)
        closed = jax.make_jaxpr(sc.step)(state0, inbox, now, nid, key)
    except Exception:  # noqa: BLE001 — TW100 reported it
        if not sc.needs_key:
            try:
                state0, inbox, now, nid, _ = _lint_avals(sc)
                closed = jax.make_jaxpr(sc.step)(
                    state0, inbox, now, nid, None)
            except Exception:  # noqa: BLE001
                return LintReport()
        else:
            return LintReport()
    return scan_jaxpr_determinism(closed.jaxpr, sc.name,
                                  host_escapes=False)


def _driver_jaxpr(engine):
    """The lowered driver: the exact entry every chunked run scans
    through (``_step_all`` — solo superstep or vmapped fleet step),
    traced with the trace plane on, same as the hand-written
    neutrality pins (tests/test_zztelemetry.py)."""
    return jax.make_jaxpr(lambda s: engine._step_all(s, True))(
        engine.init_state())


def lint_engine_jaxpr(engine, subject: Optional[str] = None
                      ) -> LintReport:
    """TW701-704 over a built engine's lowered ``_step_all`` driver —
    the step function PLUS everything the engine wraps around it
    (routing, scatters, fault masks, observability planes)."""
    name = subject or type(engine).__name__
    try:
        closed = _driver_jaxpr(engine)
    except Exception as e:  # noqa: BLE001 — report, never crash
        rep = LintReport()
        rep.add(Finding(
            "TW700", WARNING, name,
            f"engine driver is not traceable under the sanitizer "
            f"({e!r}); jaxpr determinism scan skipped"))
        return rep
    return scan_jaxpr_determinism(closed.jaxpr, name)


#: the engine knobs whose "off" must lower to the baseline jaxpr
NEUTRAL_KNOBS = ("telemetry", "record", "verify", "speculate")


def prove_mode_neutrality(build_engine, subject: str,
                          knobs: Tuple[str, ...] = NEUTRAL_KNOBS
                          ) -> LintReport:
    """TW705: generically re-prove the off-mode jaxpr-neutrality pins.
    ``build_engine(**kw)`` constructs one engine; for every knob, the
    engine built with the knob explicitly ``"off"`` must lower its
    driver to the byte-identical jaxpr of the baseline (no-argument)
    build — the zero-overhead-off contract. One INFO proof on
    success; an ERROR naming the knob on any divergence."""
    rep = LintReport()
    try:
        base = str(_driver_jaxpr(build_engine()))
    except Exception as e:  # noqa: BLE001
        rep.add(Finding(
            "TW700", WARNING, subject,
            f"baseline engine failed to build/trace under the "
            f"neutrality proof ({e!r}); TW705 skipped"))
        return rep
    bad = []
    for knob in knobs:
        try:
            off = str(_driver_jaxpr(build_engine(**{knob: "off"})))
        except TypeError:
            continue        # engine family without this knob
        except Exception as e:  # noqa: BLE001
            rep.add(Finding(
                "TW705", ERROR, subject,
                f"{knob}='off' engine failed to build/trace ({e!r}) "
                "— explicit off must be indistinguishable from the "
                "default"))
            bad.append(knob)
            continue
        if off != base:
            rep.add(Finding(
                "TW705", ERROR, subject,
                f"{knob}='off' lowers a DIFFERENT driver jaxpr than "
                "the baseline engine: the zero-overhead-off contract "
                "(docs/observability.md) requires the off mode to be "
                "jaxpr-neutral — the plane is leaking into the "
                "traced scan"))
            bad.append(knob)
    if not bad:
        rep.add(Finding(
            "TW705", INFO, subject,
            f"off-mode neutrality proof: {'/'.join(knobs)} off all "
            "lower byte-identical driver jaxprs to the baseline "
            "(zero overhead off, generically re-proven)"))
    return rep
