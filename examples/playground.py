"""Runnable network playground (≙ the reference's
`examples/playground/Main.hs:108-376`): exploratory scenarios for the
transport/dialog stack, each runnable in deterministic emulation in
milliseconds of wall-clock.

    python examples/playground.py                      # run them all
    python examples/playground.py --scenario proxy
    python examples/playground.py --scenario slowpoke --seed 3

Scenarios (reference counterpart in parentheses):

- ``yohoho``  — a server replying on the inbound connection
  (yohohoScenario, Main.hs:108-154)
- ``proxy``   — a middle node routing by header only, re-sending raw
  bytes without parsing content (proxyScenario, Main.hs:238-287)
- ``slowpoke`` — a client whose server comes up late; the lively
  socket's reconnect policy keeps retrying until it lands
  (slowpokeScenario, Main.hs:290-317)
- ``cycles``  — bind/serve/stop/re-bind the same port repeatedly;
  each server generation sees only its own traffic
  (closingServerScenario, Main.hs:320-343)
- ``forks``   — per-message-name fork strategy: inline handlers
  serialize, forked handlers overlap (pendingForkStrategy,
  Main.hs:345-376)
"""

import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(
    os.path.dirname(os.path.abspath(__file__))))

from timewarp_tpu.core.effects import GetTime, Program, Wait, fork_
from timewarp_tpu.interp.ref.des import run_emulation
from timewarp_tpu.net.backend import EmulatedBackend
from timewarp_tpu.net.delays import FixedDelay, UniformDelay
from timewarp_tpu.net.dialog import (Dialog, Listener, fork_each_message,
                                     run_inline)
from timewarp_tpu.net.message import message
from timewarp_tpu.net.transfer import AtConnTo, AtPort, Settings, Transport


@message
class Yohoho:
    """≙ the playground's rum-themed ping (Main.hs:97-106)."""
    bottles: int


@message
class EpicRequest:
    """≙ EpicRequest (Main.hs:98-106)."""
    num: int
    msg: str


def yohoho(seed: int) -> None:
    """Server replies on the inbound connection; two clients each get
    their own answers back."""
    net = EmulatedBackend(UniformDelay(1_000, 5_000), seed=seed)
    srv = Dialog(Transport(net))
    log = []

    def on_yohoho(msg, ctx) -> Program:
        t = yield GetTime()
        log.append((t, f"server: {msg.bottles} bottles from "
                       f"{ctx.peer_addr}"))
        yield from ctx.reply(EpicRequest(msg.bottles + 1, "yo-ho-ho"))

    def client(name: str, bottles: int):
        d = Dialog(Transport(net, host=name))

        def on_reply(msg, ctx) -> Program:
            t = yield GetTime()
            log.append((t, f"{name}: got {msg.num} '{msg.msg}'"))

        def run() -> Program:
            addr = ("127.0.0.1", 4100)
            yield from d.listen(AtConnTo(addr),
                                [Listener(EpicRequest, on_reply)])
            yield from d.send(addr, Yohoho(bottles))
        return d, run

    def main() -> Program:
        stop = yield from srv.listen(AtPort(4100),
                                     [Listener(Yohoho, on_yohoho)])
        d1, c1 = client("pirate-a", 15)
        d2, c2 = client("pirate-b", 99)
        yield from c1()
        yield from c2()
        yield Wait(60_000)
        for d in (d1, d2):
            yield from d.transport.close_all()
        yield from stop()

    run_emulation(main)
    for t, line in sorted(log):
        print(f"  {t:>8} µs  {line}")


def proxy(seed: int) -> None:
    """Header-routed raw forwarding: the proxy never parses content."""
    net = EmulatedBackend(FixedDelay(1_000), seed=seed)
    proxy_d = Dialog(Transport(net, host="proxy"))
    dst_d = Dialog(Transport(net, host="dest"))
    cli_d = Dialog(Transport(net, host="client"))
    dst_addr = ("dest", 4300)

    def proxy_raw(hr, ctx) -> Program:
        header, raw = hr
        name = proxy_d.packing.extract_name(raw)
        print(f"  proxy: routing header={header} name={name} "
              "(content never parsed)")
        yield from proxy_d.send_r(dst_addr, header, raw)
        return False  # gate: no local typed dispatch

    def on_arrival(msg, ctx) -> Program:
        t = yield GetTime()
        print(f"  dest @{t} µs: {msg}")

    def main() -> Program:
        stop_p = yield from proxy_d.listen(AtPort(4200), [], proxy_raw)
        stop_d = yield from dst_d.listen(
            AtPort(4300), [Listener(EpicRequest, on_arrival)])
        yield from cli_d.send_h(("proxy", 4200), ("route", 1),
                                EpicRequest(5, "via proxy"))
        yield from cli_d.send_h(("proxy", 4200), ("route", 2),
                                EpicRequest(6, "also via proxy"))
        yield Wait(50_000)
        yield from cli_d.transport.close_all()
        yield from proxy_d.transport.close_all()
        yield from stop_p()
        yield from stop_d()

    run_emulation(main)


def slowpoke(seed: int) -> None:
    """The server binds 60 ms late; the client's reconnect policy
    (retry every 20 ms, up to 10 fails) delivers anyway."""
    net = EmulatedBackend(FixedDelay(2_000), seed=seed)
    srv = Transport(net)
    cli = Transport(net, host="client", settings=Settings(
        reconnect_policy=lambda fails: 20_000 if fails < 10 else None))
    stop_holder = []

    def sink(chan, ctx) -> Program:
        from timewarp_tpu.manage.sync import CLOSED
        while True:
            item = yield from chan.get()
            if item is CLOSED:
                return
            t = yield GetTime()
            print(f"  server @{t} µs: finally received {item!r}")

    def main() -> Program:
        addr = ("127.0.0.1", 4400)
        yield from fork_(lambda: cli.send_raw(addr, b"patience pays"))

        def late_server() -> Program:
            yield Wait(60_000)
            t = yield GetTime()
            print(f"  server @{t} µs: up at last")
            stop = yield from srv.listen_raw(AtPort(4400), sink)
            stop_holder.append(stop)

        yield from fork_(late_server)
        yield Wait(200_000)
        yield from cli.close(addr)
        yield from stop_holder[0]()

    run_emulation(main)


def cycles(seed: int) -> None:
    """Three generations of a server on one port; each generation only
    sees its own messages."""
    net = EmulatedBackend(FixedDelay(500), seed=seed)
    srv = Dialog(Transport(net))
    addr = ("127.0.0.1", 4500)

    def main() -> Program:
        for gen in range(3):
            def on_msg(msg, ctx, gen=gen) -> Program:
                t = yield GetTime()
                print(f"  generation {gen} @{t} µs: {msg}")

            stop = yield from srv.listen(
                AtPort(4500), [Listener(Yohoho, on_msg)])
            cli = Dialog(Transport(net, host=f"client{gen}"))
            yield from cli.send(addr, Yohoho(gen * 10))
            yield from cli.send(addr, Yohoho(gen * 10 + 1))
            yield Wait(30_000)
            yield from cli.transport.close_all()
            yield from stop()
            print(f"  generation {gen} stopped; port re-binds cleanly")

    run_emulation(main)


def forks(seed: int) -> None:
    """Fork strategy: Yohoho handlers run inline (serialized — slow
    handler delays the next), EpicRequest handlers fork (overlap)."""
    net = EmulatedBackend(FixedDelay(1_000), seed=seed)

    def strategy(name, thunk) -> Program:
        # ≙ pendingForkStrategy: inline for one message name, the
        # default fork for everything else (Main.hs:345-376)
        if name == "Yohoho":
            return run_inline(name, thunk)
        return fork_each_message(name, thunk)

    srv = Dialog(Transport(net), fork_strategy=strategy)

    def slow_handler(kind):
        def handle(msg, ctx) -> Program:
            t0 = yield GetTime()
            yield Wait(10_000)  # pretend to work for 10 ms
            t1 = yield GetTime()
            print(f"  {kind} {msg} handled {t0}→{t1} µs")
        return handle

    def main() -> Program:
        stop = yield from srv.listen(AtPort(4600), [
            Listener(Yohoho, slow_handler("inline")),
            Listener(EpicRequest, slow_handler("forked")),
        ])
        cli = Dialog(Transport(net, host="client"))
        addr = ("127.0.0.1", 4600)
        for i in range(3):
            yield from cli.send(addr, Yohoho(i))
        for i in range(3):
            yield from cli.send(addr, EpicRequest(i, "concurrent"))
        yield Wait(120_000)
        yield from cli.transport.close_all()
        yield from stop()

    run_emulation(main)
    print("  (inline handlers end 10 ms apart; forked ones overlap)")


SCENARIOS = {
    "yohoho": yohoho,
    "proxy": proxy,
    "slowpoke": slowpoke,
    "cycles": cycles,
    "forks": forks,
}


def main() -> None:
    p = argparse.ArgumentParser(
        description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter)
    p.add_argument("--scenario", choices=sorted(SCENARIOS) + ["all"],
                   default="all")
    p.add_argument("--seed", type=int, default=0)
    a = p.parse_args()
    names = sorted(SCENARIOS) if a.scenario == "all" else [a.scenario]
    for name in names:
        print(f"== {name} ==")
        SCENARIOS[name](a.seed)


if __name__ == "__main__":
    main()
