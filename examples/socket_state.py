"""Runnable socket-state example (≙ the reference's
`examples/socket-state`): a server counting requests per client socket
via per-socket user state; roulette clients; optional nastiness.

    python examples/socket_state.py
    python examples/socket_state.py --drop 0.05   # injected resets
    python examples/socket_state.py --real
"""

import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(
    os.path.dirname(os.path.abspath(__file__))))

from timewarp_tpu.interp.aio.timed import run_real_time
from timewarp_tpu.interp.ref.des import run_emulation
from timewarp_tpu.models.socket_state_net import socket_state_net
from timewarp_tpu.net.backend import AioBackend, EmulatedBackend
from timewarp_tpu.net.delays import UniformDelay, WithDrop


def main() -> None:
    p = argparse.ArgumentParser()
    p.add_argument("--real", action="store_true")
    p.add_argument("--drop", type=float, default=0.0)
    p.add_argument("--seed", type=int, default=6)
    a = p.parse_args()
    if a.real and a.drop:
        p.error("--drop injects loss into the emulated fabric; "
                "it cannot apply to real TCP (drop --real or --drop)")
    if a.real:
        res = run_real_time(socket_state_net(
            AioBackend(), server_host="127.0.0.1", server_port=34441,
            send_interval_us=20_000, server_life_us=300_000,
            seed=a.seed))
    else:
        link = UniformDelay(1_000, 8_000)
        if a.drop:
            link = WithDrop(link, a.drop)
        res = run_emulation(socket_state_net(
            EmulatedBackend(link, seed=a.seed), seed=a.seed))
    for reqno, cid, t in res["log"]:
        print(f"{t:>10} µs  Ping #{reqno} on its socket, from client {cid}")
    print("per-socket totals:", res["per_socket"],
          "client sends:", res["client_sends"])


if __name__ == "__main__":
    main()
