"""Runnable chaos example: a token ring surviving a partition heal.

A 12-node ring with 4 circulating tokens is cut in half for a window
of virtual time (cross-cut hops are lost and counted), one node is
crash/rebooted with state loss, and the schedule heals well before the
deadline — then the ring keeps circulating the surviving tokens. The
whole thing runs under BOTH interpreters and the traces are compared
bit-for-bit: chaos stays inside the framework's parity law
(docs/faults.md).

    python examples/chaos.py
    python examples/chaos.py --nodes 16 --seed 3
"""

import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(
    os.path.dirname(os.path.abspath(__file__))))


def main() -> None:
    p = argparse.ArgumentParser()
    p.add_argument("--nodes", type=int, default=12)
    p.add_argument("--seed", type=int, default=0)
    a = p.parse_args()

    from timewarp_tpu.faults import (FaultSchedule, NodeCrash,
                                     Partition, eventually_delivered)
    from timewarp_tpu.interp.jax_engine.engine import JaxEngine
    from timewarp_tpu.interp.ref.superstep import SuperstepOracle
    from timewarp_tpu.models.token_ring import token_ring
    from timewarp_tpu.net.delays import UniformDelay
    from timewarp_tpu.trace.events import assert_traces_equal

    n = a.nodes
    half = n // 2
    sc = token_ring(n, n_tokens=6, think_us=4_000, bootstrap_us=1_000,
                    end_us=600_000, with_observer=False, mailbox_cap=8)
    link = UniformDelay(1_000, 5_000)
    heal_us = 110_000
    sched = FaultSchedule((
        # cut the ring in half for 80-110 ms: hops crossing the cut
        # (there are exactly two such edges) are lost while it is
        # live — brief enough that some tokens survive the window
        Partition((tuple(range(half)), tuple(range(half, n))),
                  80_000, heal_us),
        # and reboot one node mid-run with state loss
        NodeCrash(half - 1, 100_000, 140_000, reset_state=True),
    ))

    oracle = SuperstepOracle(sc, link, seed=a.seed, faults=sched)
    otrace = oracle.run(5000)
    engine = JaxEngine(sc, link, seed=a.seed, faults=sched)
    final, etrace = engine.run(2000)
    assert_traces_equal(otrace, etrace)

    assert eventually_delivered(etrace, heal_us), \
        "ring did not keep circulating after the heal"
    print(f"{len(etrace)} supersteps, "
          f"{etrace.total_delivered()} tokens delivered, "
          f"{int(final.fault_dropped)} messages lost to the schedule "
          f"(cut hops + reboot purges), virtual end "
          f"t={int(final.time)} µs")
    print("oracle == engine bit-for-bit; the ring survived the "
          "partition heal")


if __name__ == "__main__":
    main()
