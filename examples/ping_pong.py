"""Runnable ping-pong example (≙ the reference's `examples/ping-pong`):
two nodes over the full dialog/transport stack, emulated by default.

    python examples/ping_pong.py            # deterministic emulation
    python examples/ping_pong.py --real     # wall-clock + real TCP
"""

import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(
    os.path.dirname(os.path.abspath(__file__))))

from timewarp_tpu.interp.aio.timed import run_real_time
from timewarp_tpu.interp.ref.des import run_emulation
from timewarp_tpu.models.ping_pong_net import ping_pong_net
from timewarp_tpu.net.backend import AioBackend, EmulatedBackend
from timewarp_tpu.net.delays import UniformDelay


def main() -> None:
    p = argparse.ArgumentParser()
    p.add_argument("--real", action="store_true")
    p.add_argument("--seed", type=int, default=0)
    a = p.parse_args()
    if a.real:
        times = run_real_time(ping_pong_net(
            AioBackend(), pong_host="127.0.0.1", warmup_us=50_000))
    else:
        net = EmulatedBackend(UniformDelay(1_000, 5_000), seed=a.seed)
        times = run_emulation(ping_pong_net(net))
    for what, t in sorted(times.items(), key=lambda kv: kv[1]):
        print(f"{t:>10} µs  {what}")


if __name__ == "__main__":
    main()
