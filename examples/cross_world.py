"""The cross-world law, runnable: ONE random network, TWO worlds.

The same gossip epidemic executes as (a) a generator program over the
full network stack — per-node threads, typed dialogs, lively sockets,
the emulated byte fabric, the pure DES — and (b) a batched scenario on
the host oracle and the XLA engine. Both draw link delays from one
seeded (destination, time)-keyed model (`SeededHashUniform`, the
reference's `Delays` contract), and the delivered-rumor timeline must
match to the microsecond. This is the framework's acceptance law in
~60 lines, on genuinely random links (tests/test_cross_world*.py hold
it for token-ring, ping-pong, gossip, and praos).

    python examples/cross_world.py [--nodes 20] [--salt 7]
"""

import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

from timewarp_tpu.utils import jaxconfig  # noqa: F401,E402

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")  # instant startup

from timewarp_tpu import run_emulation  # noqa: E402
from timewarp_tpu.interp.jax_engine.engine import JaxEngine  # noqa: E402
from timewarp_tpu.interp.ref.superstep import SuperstepOracle  # noqa: E402
from timewarp_tpu.models.gossip import gossip  # noqa: E402
from timewarp_tpu.models.gossip_net import (gossip_net,  # noqa: E402
                                            gossip_net_ports)
from timewarp_tpu.net.backend import EmulatedBackend  # noqa: E402
from timewarp_tpu.net.delays import (FixedDelay,  # noqa: E402
                                     SeededHashUniform)
from timewarp_tpu.trace.events import assert_traces_equal  # noqa: E402


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--nodes", type=int, default=20)
    ap.add_argument("--salt", type=int, default=7)
    args = ap.parse_args()
    n, dur = args.nodes, 800_000
    link = SeededHashUniform(3_000, 9_000, args.salt)

    # world A: the generator-program network stack under the DES
    receipts = []
    backend = EmulatedBackend(link, connect_delays=FixedDelay(500),
                              seed=0, endpoint_ids=gossip_net_ports(n))
    run_emulation(gossip_net(backend, n, fanout=4, think_us=900,
                             bootstrap_us=100_000, duration_us=dur,
                             receipts=receipts))
    net = sorted((t, i) for t, i in receipts if t < dur)

    # world B: the batched twin on the oracle + the XLA engine
    sc = gossip(n, fanout=4, think_us=900, burst=True,
                bootstrap_us=100_000, end_us=dur, mailbox_cap=16)
    oracle = SuperstepOracle(sc, link, record_events=True)
    otrace = oracle.run(5_000)
    bat = sorted((e[4], e[2]) for e in oracle.events
                 if e[0] == "recv" and e[4] < dur)
    _, etrace = JaxEngine(sc, link).run(5_000)
    assert_traces_equal(otrace, etrace)

    print(f"net-stack world : {len(net)} rumors delivered")
    print(f"batched world   : {len(bat)} rumors delivered "
          f"(oracle ≡ engine trace)")
    if net == bat:
        print("CROSS-WORLD LAW HOLDS: every (time µs, node) identical")
        for t, i in net[:5]:
            print(f"  t={t:>7} µs  node {i}")
        print(f"  ... ({len(net) - 5} more, all equal)")
        return 0
    print("DIVERGED — first difference:")
    for a, b in zip(net, bat):
        if a != b:
            print(f"  net {a}  vs  batched {b}")
            break
    return 1


if __name__ == "__main__":
    sys.exit(main())
