"""Runnable token-ring example (≙ the reference's `examples/token-ring`,
its north-star scenario): N nodes pass an incrementing token via RPC
call/serve with an observer checking monotonic progress — one
`--emulation` flag flips the interpreter, exactly like the reference's
`emulationMode` (Main.hs:51-61).

    python examples/token_ring.py                  # emulated (instant)
    python examples/token_ring.py --no-emulation   # wall-clock asyncio
    python examples/token_ring.py --engine         # batched XLA engine
"""

import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(
    os.path.dirname(os.path.abspath(__file__))))


def main() -> None:
    p = argparse.ArgumentParser()
    p.add_argument("--no-emulation", action="store_true",
                   help="real wall-clock mode (scaled-down timings)")
    p.add_argument("--engine", action="store_true",
                   help="run the batched-engine form instead (token_ring "
                        "state-machine scenario on JaxEngine)")
    p.add_argument("--nodes", type=int, default=5)
    p.add_argument("--seed", type=int, default=0)
    a = p.parse_args()

    if a.engine:
        from timewarp_tpu.interp.jax_engine.engine import JaxEngine
        from timewarp_tpu.models.token_ring import (token_ring,
                                                    token_ring_links)
        sc = token_ring(a.nodes, think_us=30_000, bootstrap_us=10_000,
                        end_us=500_000, with_observer=True)
        final, trace = JaxEngine(sc, token_ring_links(a.nodes),
                                 seed=a.seed).run(2000)
        print(f"{len(trace)} supersteps, {trace.total_delivered()} "
              f"messages delivered, virtual end t={int(final.time)} µs")
        return

    from timewarp_tpu.interp.aio.timed import run_real_time
    from timewarp_tpu.interp.ref.des import run_emulation
    from timewarp_tpu.models.token_ring_net import (token_ring_delays,
                                                    token_ring_net)
    from timewarp_tpu.net.backend import EmulatedBackend
    from timewarp_tpu.net.delays import FixedDelay

    # scaled-down timings so the wall-clock mode finishes in ~2 s
    net = EmulatedBackend(token_ring_delays(),
                          connect_delays=FixedDelay(1), seed=a.seed)
    prog = token_ring_net(
        net, a.nodes, duration_us=2_000_000, passing_delay_us=300_000,
        bootstrap_us=100_000, check_period_us=500_000,
        allowed_progress_delay_us=1_000_000)
    run = run_real_time if a.no_emulation else run_emulation
    notes, errors = run(prog)
    for t, v in notes:
        print(f"{t:>10} µs  observer noted token value {v}")
    print("errors:", errors or "none")


if __name__ == "__main__":
    main()
