"""Stage isolation for the praos superstep at 2^20 nodes (round 5).

Each stage is jitted alone inside a 32-iteration fori_loop with
host-readback sync; numbers carry the dispatch/loop floor, so read
deltas. Run after iter_r05.py showed the adaptive routing landed at
~30 ms/superstep — the question is where the [K,N] base goes.
"""

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

from timewarp_tpu.utils import jaxconfig  # noqa: F401

import jax
import jax.numpy as jnp
from jax import lax

from iter_r05 import praos_engine, calib

REPS = 32


def timeit(name, fn, *args):
    f = jax.jit(fn)
    out = f(*args)
    leaf = jax.tree.leaves(out)[0]
    int(jnp.asarray(leaf).reshape(-1)[0])  # readback sync
    t0 = time.perf_counter()
    out = f(*args)
    int(jnp.asarray(jax.tree.leaves(out)[0]).reshape(-1)[0])
    dt1 = time.perf_counter() - t0
    print(json.dumps({"stage": name, "ms": round(dt1 * 1e3, 2)}))


def loop(name, fn, *args):
    """fn must map its first arg to same-shape output; 32 iterations."""
    def rep(x, *rest):
        def body(i, x):
            return fn(x, *rest)
        return lax.fori_loop(0, REPS, body, x)
    f = jax.jit(rep)
    out = f(*args)
    int(jnp.asarray(jax.tree.leaves(out)[0]).reshape(-1)[0])
    t0 = time.perf_counter()
    out = f(*args)
    int(jnp.asarray(jax.tree.leaves(out)[0]).reshape(-1)[0])
    dt = (time.perf_counter() - t0) / REPS
    print(json.dumps({"stage": name, "ms_per_iter": round(dt * 1e3, 3)}))


def main():
    calib()
    eng = praos_engine()
    sc = eng.scenario
    st = eng.init_state()
    st = eng.run_quiet(24, st)
    int(st.delivered)
    K, M, P = sc.mailbox_cap, sc.max_out, sc.payload_width
    n = sc.n_nodes
    print(json.dumps({"n": n, "K": K, "M": M, "P": P}))

    I32MAX = jnp.int32(2**31 - 1)
    NEVER = jnp.int64((1 << 62))

    # A: next-event reduction
    def next_ev(mb_rel, wake, t):
        nnr = mb_rel.min(axis=0)
        node_next = jnp.minimum(
            wake, jnp.where(nnr == I32MAX, NEVER,
                            t + nnr.astype(jnp.int64)))
        return mb_rel + (node_next.min() % 7).astype(jnp.int32)
    loop("A next-event [K,N]+[N]", lambda x: next_ev(x, st.wake, st.time),
         st.mb_rel)

    # B: deliver mask + commutative inbox wheres ([K,N] + [K,P,N])
    def inbox(mb_rel, mb_pay, wake, t):
        live = mb_rel < I32MAX
        nnr = mb_rel.min(axis=0)
        node_next = jnp.minimum(
            wake, jnp.where(nnr == I32MAX, NEVER,
                            t + nnr.astype(jnp.int64)))
        tmin = node_next.min()
        fire = (node_next < NEVER) & (node_next - tmin < 8000)
        nrel = jnp.minimum(node_next - t, jnp.int64(2**31 - 2)
                           ).astype(jnp.int32)
        deliver = live & (mb_rel <= nrel[None, :]) & fire[None, :]
        itime = jnp.where(deliver, t + mb_rel.astype(jnp.int64), NEVER)
        ipay = jnp.where(deliver[:, None, :], mb_pay, 0)
        return (ipay + itime[:, None, :].astype(jnp.int32)) % 5
    loop("B deliver+inbox wheres", lambda x: inbox(st.mb_rel, x, st.wake,
                                                   st.time),
         st.mb_payload)

    # C: free-rows single-operand sort [K,N]
    slots = jnp.broadcast_to(jnp.arange(K, dtype=jnp.int32)[:, None],
                             (K, n))
    def freerows(mb_rel):
        keep = mb_rel < I32MAX
        return lax.sort(jnp.where(keep, jnp.int32(K), slots), dimension=0)
    loop("C free-rows sort [K,N]", lambda x: freerows(x) % 3 + x % 2,
         st.mb_rel)

    # D: sender compaction sort [N] single operand
    ids = jnp.arange(n, dtype=jnp.int32)
    def sender_sort(x):
        livemask = (x[0] % 97) < 3   # ~3% active
        return lax.sort(jnp.where(livemask, ids, jnp.int32(n)))[None, :]
    loop("D sender sort [N] 1-op", lambda x: sender_sort(x) % 5 + x % 2,
         st.mb_rel)

    # E: the vmap'd step function alone (praos leader check + adopt)
    from timewarp_tpu.core.rng import fire_bits
    from timewarp_tpu.core.scenario import Inbox
    node_ids = jnp.arange(n, dtype=jnp.int32)
    def stepfn(mb_rel, mb_pay, states):
        deliver = mb_rel < I32MAX
        ib = Inbox(valid=deliver,
                   src=jnp.zeros((K, n), jnp.int32),
                   time=jnp.where(deliver,
                                  st.time + mb_rel.astype(jnp.int64),
                                  NEVER),
                   payload=jnp.where(deliver[:, None, :], mb_pay, 0))
        now_vec = jnp.full((n,), st.time + 1000)
        bits = fire_bits(eng.s0, eng.s1, node_ids, now_vec)
        from timewarp_tpu.core.scenario import Outbox
        ns, out, nw = jax.vmap(
            sc.step,
            in_axes=(0, Inbox(valid=-1, src=-1, time=-1, payload=-1),
                     0, 0, 0),
            out_axes=(0, Outbox(valid=-1, dst=-1, payload=-1), 0))(
                states, ib, now_vec, node_ids, bits)
        return mb_rel % 3 + \
            jax.tree.leaves(ns)[0][None, :n].astype(jnp.int32)
    loop("E inbox+step vmap", lambda x: stepfn(x, st.mb_payload,
                                               st.states) % 7 + x % 2,
         st.mb_rel)

    # F: full superstep for reference
    step = lambda s: eng._superstep(s, False)[0]
    def full(s):
        def body(i, s):
            return step(s)
        return lax.fori_loop(0, REPS, body, s)
    f = jax.jit(full)
    out = f(st)
    int(out.delivered)
    t0 = time.perf_counter()
    out = f(st)
    int(out.delivered)
    dt = (time.perf_counter() - t0) / REPS
    print(json.dumps({"stage": "F FULL superstep",
                      "ms_per_iter": round(dt * 1e3, 3)}))


if __name__ == "__main__":
    main()
