"""Round-5 micro-benchmarks, RTT-corrected.

The axon tunnel adds a ~110 ms round-trip to ANY host sync (readback
or block_until_ready — profiling/access_micro_r05.py session log), so
every op here runs inside a 256-iteration fori_loop: the RTT bias per
iteration is ~0.45 ms and the printed numbers subtract the measured
no-op loop floor. These are the numbers the sparse-superstep design
actually stands on.
"""

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

from timewarp_tpu.utils import jaxconfig  # noqa: F401

import jax
import jax.numpy as jnp
from jax import lax

N = 1 << 20
A = 1 << 17
K = 16
REPS = int(os.environ.get("TW_REPS", 256))

_floor_ms = 0.0


def loop(name, fn, *args, note=""):
    global _floor_ms
    def rep(x, *rest):
        def body(i, x):
            return fn(x, i, *rest)
        return lax.fori_loop(jnp.int32(0), jnp.int32(REPS), body, x)
    f = jax.jit(rep)
    out = f(*args)
    int(jnp.asarray(jax.tree.leaves(out)[0]).reshape(-1)[0] % 997)
    best = 1e9
    for _ in range(2):
        t0 = time.perf_counter()
        out = f(*args)
        int(jnp.asarray(jax.tree.leaves(out)[0]).reshape(-1)[0] % 997)
        best = min(best, (time.perf_counter() - t0) / REPS)
    ms = best * 1e3
    if name == "noop":
        _floor_ms = ms
    print(json.dumps({"op": name, "ms": round(ms - _floor_ms, 4),
                      "raw_ms": round(ms, 4), **({"note": note}
                                                 if note else {})}))


def main():
    key = jax.random.PRNGKey(0)
    idx = jax.random.randint(key, (A,), 0, N, dtype=jnp.int32)
    x1 = jnp.arange(N, dtype=jnp.int32)
    x2 = jnp.tile(x1[None, :], (K, 1))
    x8 = jnp.tile(x1, 8)                         # [8M]
    print(json.dumps({"REPS": REPS}))

    loop("noop", lambda x, i: x + i, jnp.int32(3))
    loop("ew [16M] 3 passes",
         lambda x, i: jnp.where(x > i, x - 1, x + 1) ^ (x >> 1), x2)
    loop("reduce [16,1M] min axis0",
         lambda x, i: x.at[0].set(x.min(axis=0) + i), x2)
    loop("sort 1M 1-op", lambda x, i: lax.sort(x ^ i), x1)
    loop("sort 8M 1-op", lambda x, i: lax.sort(x ^ i), x8)
    loop("sort 8M 3-op 3-key",
         lambda x, i: lax.sort((x ^ i, x, x), dimension=0,
                               num_keys=3)[0], x8)
    loop("sort 1M 3-op 3-key",
         lambda x, i: lax.sort((x ^ i, x, x), dimension=0,
                               num_keys=3)[0], x1)
    loop("sort 131k 1-op", lambda x, i: lax.sort(x ^ i), idx)
    loop("sort 131k 5-op 3-key",
         lambda x, i: lax.sort((x ^ i, x, x, x, x), dimension=0,
                               num_keys=3)[0], idx)
    loop("sort [16,1M] short-axis 1-op",
         lambda x, i: lax.sort(x ^ i, dimension=0), x2)
    loop("sort [1024,1024] minor 1-op",
         lambda x, i: lax.sort((x ^ i).reshape(1024, 1024),
                               dimension=1).reshape(N), x1)
    loop("gather 1D 131k from 1M",
         lambda x, i: x.at[:A].set(x[(idx ^ i) % N]), x1)
    loop("gather 1D 1M from 1M",
         lambda x, i: x[(x ^ i) % N], x1)
    loop("scatter 1D 131k into 1M",
         lambda x, i: x.at[(idx ^ i) % N].set(i, mode="drop"), x1)
    loop("scatter 1D 1M into 1M",
         lambda x, i: x.at[(x ^ i) % N].set(i, mode="drop"), x1)
    loop("scatter 2D 131k into [16,1M]",
         lambda x, i: x.at[(idx ^ i) % K, (idx ^ (i * 7)) % N].set(
             i, mode="drop"), x2)
    loop("scatter 2D 1M into [16,1M]",
         lambda x, i: x.at[(x[0] ^ i) % K, (x[1] ^ (i * 7)) % N].set(
             i, mode="drop"), x2)
    # threefry-ish elementwise chain (link sampling cost model)
    def tf(x, i):
        y = x.astype(jnp.uint32)
        for r in range(20):
            y = (y * jnp.uint32(2654435761) + jnp.uint32(r * 97 + 1)
                 ) ^ (y >> 13)
        return y.astype(jnp.int32)
    loop("60ish-op chain [131k]", tf, idx)
    loop("60ish-op chain [8M]", tf, x8)
    # lognormal transcendentals at 131k
    def logn(x, i):
        u = (x ^ i).astype(jnp.float32) / 2**31 + 1.0001
        z = jnp.exp(jnp.log(u) * 0.6) * 20000.0
        return (z.astype(jnp.int32))
    loop("exp/log f32 [131k]", logn, idx)


if __name__ == "__main__":
    main()
