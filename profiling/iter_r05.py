"""Round-5 iteration harness: in-loop superstep cost for the two
sparse laggard configs (gossip_100k wave, praos_1m), synced by host
readback (NOT block_until_ready — not a true sync on this tunnel
backend, PERF_r04.md). Run repeatedly while optimizing the lazy
insertion path; trust deltas within one session (calib printed first).

Usage: python profiling/iter_r05.py [wave|praos|steady] [steps]
"""

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

from timewarp_tpu.utils import jaxconfig  # noqa: F401

import jax
import jax.numpy as jnp
from jax import lax


def calib():
    @jax.jit
    def kern(x):
        def body(i, x):
            return lax.sort(x * jnp.int32(1103515245) + i)
        return lax.fori_loop(jnp.int32(0), jnp.int32(64), body, x)
    x = jnp.arange(1 << 20, dtype=jnp.int32)
    int(kern(x)[0])
    t0 = time.perf_counter()
    int(kern(x)[0])
    print(json.dumps({"calib_s": round(time.perf_counter() - t0, 4)}))


def wave_engine(n=100_000):
    from timewarp_tpu.interp.jax_engine.engine import JaxEngine
    from timewarp_tpu.models.gossip import gossip, gossip_links
    from timewarp_tpu.net.delays import Quantize
    sc = gossip(n, fanout=8, think_us=2_000, burst=True,
                end_us=5_000_000, mailbox_cap=16)
    link = Quantize(gossip_links(median_us=20_000, sigma=0.6,
                                 floor_us=8_000), 1_000)
    cap = None
    if os.environ.get("TW_LEGACY_CAP"):
        cap = min(1 << 17, n * 8)
    return JaxEngine(sc, link, window=8_000, route_cap=cap)


def praos_engine(n=1 << 20):
    from timewarp_tpu.interp.jax_engine.engine import JaxEngine
    from timewarp_tpu.models.praos import praos
    from timewarp_tpu.net.delays import LogNormalDelay, Quantize
    sc = praos(n, slot_us=1_000_000, n_slots=1 << 30,
               leader_prob=4.0 / n, fanout=8, burst=True,
               mailbox_cap=16)
    link = Quantize(LogNormalDelay(20_000, 0.6, cap_us=150_000,
                                   floor_us=8_000), 1_000)
    cap = None
    if os.environ.get("TW_LEGACY_CAP"):
        cap = min(3 << 19, n * 8)
    return JaxEngine(sc, link, window=8_000, route_cap=cap)


def steady_engine(n=1 << 20):
    from timewarp_tpu.interp.jax_engine.engine import JaxEngine
    from timewarp_tpu.models.gossip import gossip
    from timewarp_tpu.net.delays import Quantize, UniformDelay
    sc = gossip(n, fanout=1, think_us=1_000, gossip_interval=1_000,
                end_us=(1 << 50), steady=True, mailbox_cap=8)
    link = Quantize(UniformDelay(500, 4_500), 1_000)
    return JaxEngine(sc, link)


def main():
    which = sys.argv[1] if len(sys.argv) > 1 else "wave"
    steps = int(sys.argv[2]) if len(sys.argv) > 2 else 0
    calib()
    eng = {"wave": wave_engine, "praos": praos_engine,
           "steady": steady_engine}[which]()
    warm = {"wave": 8, "praos": 16, "steady": 64}[which]
    msteps = steps or {"wave": 60, "praos": 64, "steady": 64}[which]
    st = eng.init_state()
    t0 = time.perf_counter()
    st = eng.run_quiet(warm, st)
    int(st.delivered)
    print(json.dumps({"compile_plus_warm_s":
                      round(time.perf_counter() - t0, 2)}))
    t0 = time.perf_counter()
    fin = eng.run_quiet(msteps, st)
    delivered = int(fin.delivered) - int(st.delivered)
    dt = time.perf_counter() - t0
    nsteps = int(fin.steps) - int(st.steps)
    print(json.dumps({
        "config": which,
        "steps": nsteps,
        "ms_per_superstep": round(dt * 1e3 / max(nsteps, 1), 3),
        "delivered": delivered,
        "msg_per_s": round(delivered / dt, 1),
        "route_drop": int(fin.route_drop),
        "short_delay": int(fin.short_delay),
        "overflow": int(fin.overflow),
    }))


if __name__ == "__main__":
    main()
