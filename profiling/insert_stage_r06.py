"""Round-12 insert-stage microbench: the Pallas insertion kernel vs
the XLA scatter baselines, RTT-corrected.

The on-chip hook for ISSUE 8's acceptance: isolate the mailbox
insertion stage at the praos bench shape and time, floor-subtracted
inside a REPS-iteration device loop (the r5 methodology — every host
sync through the tunnel costs ~110 ms, so per-op numbers must come
from device loops with the no-op floor subtracted,
profiling/micro2_r05.py):

- ``insert_xla``   — flat 1D scatters (the engine default; pays the
  tiled-[K, N] relayout copy at the scatter operand, PERF_r05.md §3);
- ``insert_xla2d`` — the 2D [col, row] scatter form (no relayout, ~7x
  the flat scatter in isolation — the baseline the kernel must beat);
- ``insert_pallas`` — the in-tile insertion kernel (pallas_insert.py:
  mailbox planes streamed through VMEM once, holes ranked in-tile);
- ``firecompact``  — the fire-compaction kernel alone (the front end
  that replaces the sender-compaction N-sort + rung-width gathers);
- ``ladder_front`` — the XLA front end it replaces (sender sort +
  top-rung gathers), for the head-to-head.

Each line reports achieved GB/s against the streaming bytes model and
the fraction of the assumed HBM roofline (``TW_HBM_GBPS``, default
270 — the r5 dense-ring floor implies ~266 GB/s on this chip). On a
CPU host the kernels run under the Pallas interpreter: the timings
are then NOT hardware statements (the JSON says platform=cpu) — run
this on a chip-attached round and paste the lines into the PERF
notes (PERF_r06.md records the CPU-only caveat until then).

Env knobs: TW_NODES (default 2^20), TW_MAXOUT (8), TW_CAP (mailbox
cap, 16), TW_PAYLOAD (2), TW_BATCH (resident batch messages, 2^17),
TW_REPS (64), TW_HBM_GBPS (270).
"""

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

from timewarp_tpu.utils import jaxconfig  # noqa: F401

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

N = int(os.environ.get("TW_NODES", 1 << 20))
M = int(os.environ.get("TW_MAXOUT", 8))
K = int(os.environ.get("TW_CAP", 16))
P = int(os.environ.get("TW_PAYLOAD", 2))
S = int(os.environ.get("TW_BATCH", 1 << 17))
REPS = int(os.environ.get("TW_REPS", 64))
GBPS = float(os.environ.get("TW_HBM_GBPS", 270))

_floor_ms = 0.0


def loop(name, fn, state, bytes_step, note=""):
    """Device-loop timing with the no-op floor subtracted: ``fn(state,
    i) -> state`` runs REPS times inside one jitted fori_loop; the
    readback at the end is the single host sync."""
    global _floor_ms

    def rep(state):
        return lax.fori_loop(jnp.int32(0), jnp.int32(REPS),
                             lambda i, s: fn(s, i), state)

    f = jax.jit(rep)
    out = f(state)
    int(jnp.asarray(jax.tree.leaves(out)[0]).reshape(-1)[0] % 997)
    best = 1e9
    for _ in range(2):
        t0 = time.perf_counter()
        out = f(state)
        int(jnp.asarray(jax.tree.leaves(out)[0]).reshape(-1)[0] % 997)
        best = min(best, (time.perf_counter() - t0) / REPS)
    ms = best * 1e3
    if name == "noop":
        _floor_ms = ms
        print(json.dumps({"op": name, "raw_ms": round(ms, 4)}))
        return
    net = max(ms - _floor_ms, 1e-6)
    gbs = bytes_step / (net * 1e-3) / 1e9
    print(json.dumps({
        "op": name, "ms": round(net, 4), "raw_ms": round(ms, 4),
        "achieved_gbps": round(gbs, 1),
        "hbm_frac": round(gbs / GBPS, 4),
        **({"note": note} if note else {})}))


def main():
    from timewarp_tpu.interp.jax_engine.engine import JaxEngine
    from timewarp_tpu.models.praos import praos
    from timewarp_tpu.net.delays import Quantize, UniformDelay

    sc = praos(N, slot_us=1_000_000, n_slots=1 << 30,
               leader_prob=4.0 / N, fanout=M, burst=True,
               mailbox_cap=K)
    link = Quantize(UniformDelay(8_000, 30_000), 1_000)
    mode = "pallas" if jax.default_backend() == "tpu" else "interpret"
    engines = {
        "insert_xla": JaxEngine(sc, link, window="auto", lint="off"),
        "insert_xla2d": JaxEngine(sc, link, window="auto",
                                  lint="off", insert="xla2d"),
        "insert_pallas": JaxEngine(
            sc, link, window="auto", lint="off", insert=mode,
            insert_cap=min(S, N * sc.max_out)),
    }
    Pw = sc.payload_width
    SS = engines["insert_pallas"]._pallas_stage.S
    rng = np.random.RandomState(0)
    sd = jnp.asarray(np.sort(rng.randint(0, N, size=SS))
                     .astype(np.int32))
    src = jnp.asarray(rng.randint(0, N, size=SS).astype(np.int32))
    pay = tuple(jnp.asarray(rng.randint(0, 1 << 20, size=SS)
                            .astype(np.int32)) for _ in range(Pw))
    ok = sd < N
    fr_dt = jnp.int8 if K <= 127 else jnp.int32
    free_rows = jnp.broadcast_to(
        jnp.arange(K, dtype=fr_dt)[:, None], (K, N))
    st = engines["insert_xla"].init_state()
    planes = K * (1 + Pw + (1 if sc.inbox_src else 0))
    ins_bytes = 2 * planes * N * 4 + (3 + Pw) * SS * 4

    print(json.dumps({"config": {
        "n": N, "max_out": M, "mailbox_cap": K, "payload": Pw,
        "batch": SS, "reps": REPS, "hbm_gbps_assumed": GBPS,
        "platform": jax.default_backend(), "insert_mode": mode}}))
    loop("noop", lambda s, i: s, st.mb_rel, 0)

    for name, eng in engines.items():
        def body(mb_rel, i, eng=eng):
            # vary drel per iteration so the loop cannot CSE
            drel = (sd * jnp.int32(1103515245) + i).astype(jnp.int32) \
                | jnp.int32(1)
            out = eng._insert_sorted(
                mb_rel, st.mb_src, st.mb_payload, sd, ok,
                jnp.abs(drel) % jnp.int32(1 << 20) + 1, src, pay,
                free_rows, None)
            return out[0]
        loop(name, body, st.mb_rel, ins_bytes)

    # the two front ends, head-to-head: fire-compaction kernel vs the
    # sender sort + top-rung gathers it replaces
    peng = engines["insert_pallas"]
    stage = peng._pallas_stage
    pdst0 = jnp.where(
        jnp.asarray(rng.rand(M, N) < (SS / (2.0 * M * N))),
        jnp.asarray(rng.randint(0, N, size=(M, N)).astype(np.int32)),
        jnp.int32(-1))
    payload = jnp.asarray(
        rng.randint(0, 1 << 20, size=(M, Pw, N)).astype(np.int32))
    woff_n = jnp.zeros((N,), jnp.int32)
    fc_bytes = (M * (1 + Pw) * N + (3 + Pw) * SS) * 4

    def fc_body(acc, i):
        pdst = jnp.where(pdst0 >= 0, (pdst0 + i) % jnp.int32(N),
                         jnp.int32(-1))
        d, w, smr, pc, drop = stage.compact(pdst, woff_n, payload)
        return acc + d[:1] + drop
    loop("firecompact", fc_body, jnp.zeros((1,), jnp.int32), fc_bytes)

    node_ids = jnp.arange(N, dtype=jnp.int32)
    lf_bytes = (N + M * (1 + Pw) * N + (2 + Pw) * SS) * 4

    def ladder_body(acc, i):
        pdst = jnp.where(pdst0 >= 0, (pdst0 + i) % jnp.int32(N),
                         jnp.int32(-1))
        live = jnp.any(pdst >= 0, axis=0)
        sid_sorted = lax.sort(jnp.where(live, node_ids, jnp.int32(N)))
        A = SS // M
        sids = lax.slice_in_dim(sid_sorted, 0, A)
        real = sids < N
        sidc = jnp.where(real, sids, 0)
        dst_a = jnp.take(pdst, sidc, axis=1)
        pay_a = [jnp.take(payload[:, p, :], sidc, axis=1)
                 for p in range(Pw)]
        return acc + dst_a[0, :1] + sum(p[0, :1] for p in pay_a)
    loop("ladder_front", ladder_body, jnp.zeros((1,), jnp.int32),
         lf_bytes,
         note="sender sort + top-rung gathers (what firecompact "
              "replaces)")


if __name__ == "__main__":
    main()
