"""Per-piece timing of the engine superstep on the current backend.

Times the building blocks of the *round-1* `JaxEngine._superstep`
design in isolation at the bench shapes (pieces 3-6 measure the old
int64-lexsort/scatter path on purpose — they are the evidence behind
profiling/superstep_breakdown.md), then the full current superstep.
Run on TPU (default platform) or CPU (JAX_PLATFORMS=cpu).

Caveat from the breakdown doc: isolated per-dispatch numbers through
the axon tunnel are unreliable; trust only the in-scan FULL-superstep
figures at the bottom.
"""

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

from timewarp_tpu.utils import jaxconfig  # noqa: F401

import jax
import jax.numpy as jnp
import numpy as np

from timewarp_tpu.core.rng import fire_bits, msg_bits
from timewarp_tpu.core.scenario import NEVER
from timewarp_tpu.interp.jax_engine.engine import JaxEngine
from timewarp_tpu.models.token_ring import token_ring
from timewarp_tpu.net.delays import FixedDelay

N = int(os.environ.get("TW_PROF_NODES", 65536))
K = 4
M = 2
P = 2
REPS = int(os.environ.get("TW_PROF_REPS", 20))


def bench(name, fn, *args):
    fn2 = jax.jit(fn)
    out = jax.block_until_ready(fn2(*args))  # compile
    t0 = time.perf_counter()
    for _ in range(REPS):
        out = fn2(*args)
    jax.block_until_ready(out)
    dt = (time.perf_counter() - t0) / REPS
    print(json.dumps({"piece": name, "ms": round(dt * 1e3, 3)}))
    return dt


def main():
    print(json.dumps({"platform": jax.devices()[0].platform, "N": N}))
    key = jax.random.PRNGKey(0)
    node_ids = jnp.arange(N, dtype=jnp.int32)
    t = jnp.int64(12345)
    mb_time = jnp.where(
        jax.random.bernoulli(key, 0.5, (N, K)),
        jnp.int64(12345), NEVER)
    mb_valid = mb_time < NEVER
    mb_src = jnp.zeros((N, K), jnp.int32)
    mb_payload = jnp.zeros((N, K, P), jnp.int32)
    slots = jnp.broadcast_to(jnp.arange(K, dtype=jnp.int32), (N, K))

    S = N * M
    src_f = jnp.repeat(node_ids, M)
    slot_f = jnp.tile(jnp.arange(M, dtype=jnp.int32), N)
    dst_f = (src_f + 1) % N
    v_f = jnp.ones((S,), bool)

    # 1. fire entropy derivation (elementwise threefry, core/rng.py)
    bench("fire_bits [N]",
          lambda s: fire_bits(1, s, node_ids, t)[0], jnp.uint32(2))

    # 2. msg entropy derivation (elementwise threefry x3)
    bench("msg_bits [N*M]",
          lambda s: msg_bits(1, s, src_f, dst_f, t, slot_f)[0],
          jnp.uint32(2))

    # 3. inbox lexsort (3 keys incl. int64, [N, K])
    deliver = mb_valid
    bench("inbox lexsort [N,K]",
          lambda d, mt: jnp.lexsort((slots, mt, ~d), axis=-1), deliver,
          mb_time)

    # 4. compaction lexsort (2 keys, [N, K])
    bench("compact lexsort [N,K]",
          lambda kp: jnp.lexsort((slots, ~kp), axis=-1), mb_valid)

    # 5. routing argsort + searchsorted over S
    def route(dst, ok):
        sort_dst = jnp.where(ok, dst, N)
        perm3 = jnp.argsort(sort_dst, stable=True)
        sd = sort_dst[perm3]
        rank = jnp.arange(S, dtype=jnp.int32) - jnp.searchsorted(
            sd, sd, side="left").astype(jnp.int32)
        return perm3, rank
    bench("route argsort+searchsorted [S]", route, dst_f, v_f)

    # 6. mailbox scatter (4x .at[row, col].set)
    row = dst_f
    col = jnp.zeros((S,), jnp.int32)
    def scatter(mt, ms_, mp, mv):
        mt = mt.at[row, col].set(t, mode="drop")
        ms_ = ms_.at[row, col].set(src_f, mode="drop")
        mp = mp.at[row, col].set(jnp.zeros((S, P), jnp.int32), mode="drop")
        mv = mv.at[row, col].set(True, mode="drop")
        return mt, ms_, mp, mv
    bench("mailbox scatter x4", scatter, mb_time, mb_src, mb_payload,
          mb_valid)

    # 7. trace digests
    from timewarp_tpu.trace.hashing import FIRED, mix32_jnp
    bench("digest mix32 [N,K]x2",
          lambda s: (mix32_jnp(FIRED, s, s, s, s).astype(jnp.uint32).sum(),
                     mix32_jnp(FIRED, s, s).astype(jnp.uint32).sum()),
          mb_src)

    # 8. full current superstep
    sc = token_ring(N, n_tokens=N, think_us=0, bootstrap_us=1_000,
                    end_us=(1 << 50), with_observer=False, mailbox_cap=K)
    engine = JaxEngine(sc, FixedDelay(500))
    st = jax.block_until_ready(engine.init_state())
    st = jax.block_until_ready(engine.run_quiet(2, st))  # mid-flight state

    step = jax.jit(lambda s: engine._superstep(s, False)[0])
    out = jax.block_until_ready(step(st))
    t0 = time.perf_counter()
    cur = st
    for _ in range(REPS):
        cur = step(cur)
    jax.block_until_ready(cur)
    dt = (time.perf_counter() - t0) / REPS
    print(json.dumps({"piece": "FULL superstep (jit, dispatched per step)",
                      "ms": round(dt * 1e3, 3)}))

    # 9. full superstep inside while_loop (no per-step dispatch)
    st2 = jax.block_until_ready(engine.run_quiet(2, st))
    t0 = time.perf_counter()
    fin = jax.block_until_ready(engine.run_quiet(REPS * 4, st2))
    dt = (time.perf_counter() - t0) / (REPS * 4)
    print(json.dumps({"piece": "FULL superstep (while_loop)",
                      "ms": round(dt * 1e3, 3),
                      "delivered": int(fin.delivered - st2.delivered)}))


if __name__ == "__main__":
    main()
