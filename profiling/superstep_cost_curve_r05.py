"""Cost of ONE praos/wave superstep as a function of its load.

Deterministic sim => any superstep is reproducible: run k steps from
init, then measure that single superstep by repeating it REPS times in
a fori_loop (the carry perturbs only the `steps` counter, which feeds
nothing downstream, so XLA cannot hoist the loop body). RTT-corrected
by the loop length.

Usage: python profiling/superstep_cost_curve_r05.py [praos|wave]
"""

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))
sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

from timewarp_tpu.utils import jaxconfig  # noqa: F401

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from iter_r05 import praos_engine, wave_engine

REPS = 64


def one_superstep_cost(eng, st):
    def rep(s0):
        def body(i, carry):
            s = s0._replace(steps=s0.steps + i)   # defeats hoisting
            out = eng._superstep(s, False)[0]
            # thread data-dependence bits through the routing and
            # insertion outputs so XLA cannot DCE them
            dep = (out.mb_rel[0, 0].astype(jnp.int64) & 1) ^ \
                (out.mb_payload[0, 0, 0].astype(jnp.int64) & 1) ^ \
                (out.wake[0] & 1)
            return carry._replace(
                delivered=out.delivered + dep, time=out.time,
                overflow=out.overflow)
        return lax.fori_loop(jnp.int32(0), jnp.int32(REPS), body, s0)
    f = jax.jit(rep)
    out = f(st)
    int(out.delivered)
    best = 1e9
    for _ in range(2):
        t0 = time.perf_counter()
        out = f(st)
        int(out.delivered)
        best = min(best, (time.perf_counter() - t0) / REPS)
    return best * 1e3


def main():
    which = sys.argv[1] if len(sys.argv) > 1 else "praos"
    eng = {"praos": praos_engine, "wave": wave_engine}[which]()
    warm = {"praos": 24, "wave": 8}[which]
    st = eng.init_state()
    st = eng.run_quiet(warm, st)
    int(st.delivered)
    fin, tr = eng.run(128, st)
    sent = np.asarray(tr.sent_count)
    fired = np.asarray(tr.fired_count)
    # pick superstep indices spanning the load range
    order = np.argsort(sent)
    picks = sorted(set(
        int(order[int(q * (len(order) - 1))])
        for q in (0.0, 0.5, 0.75, 0.9, 0.97, 1.0)))
    print(json.dumps({"n_steps": len(sent),
                      "sent_p50": int(np.percentile(sent, 50)),
                      "sent_max": int(sent.max())}))
    for j in picks:
        stj = eng.run_quiet(j, st) if j else st
        int(stj.delivered)
        ms = one_superstep_cost(eng, stj)
        print(json.dumps({
            "step": j, "sent": int(sent[j]), "fired": int(fired[j]),
            "ms": round(ms, 3)}))


if __name__ == "__main__":
    main()
