"""Which axis does the 22.8 ms praos superstep scale with?

Run the praos config with one structural knob varied at a time
(fanout/M, mailbox_cap/K, n) over long windows; the scaling axis
locates the dominant cost. Usage:
  python profiling/praos_axes_r05.py [fanout mailbox n_half base]
"""

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

from timewarp_tpu.utils import jaxconfig  # noqa: F401

import jax

from timewarp_tpu.interp.jax_engine.engine import JaxEngine
from timewarp_tpu.models.praos import praos
from timewarp_tpu.net.delays import LogNormalDelay, Quantize


def build(n=1 << 20, fanout=8, mailbox=16):
    sc = praos(n, slot_us=1_000_000, n_slots=1 << 30,
               leader_prob=4.0 / n, fanout=fanout, burst=True,
               mailbox_cap=mailbox)
    link = Quantize(LogNormalDelay(20_000, 0.6, cap_us=150_000,
                                   floor_us=8_000), 1_000)
    return JaxEngine(sc, link, window="auto")


def run(name, eng, warm=24, steps=192):
    st = eng.init_state()
    st = eng.run_quiet(warm, st)
    int(st.delivered)
    t0 = time.perf_counter()
    fin = eng.run_quiet(steps, st)
    d = int(fin.delivered) - int(st.delivered)
    dt = time.perf_counter() - t0
    ns = int(fin.steps) - int(st.steps)
    print(json.dumps({"variant": name, "steps": ns,
                      "ms_per_superstep": round(dt * 1e3 / ns, 2),
                      "delivered": d}))


def main():
    which = sys.argv[1:] or ["base", "fanout", "mailbox", "n_half"]
    if "base" in which:
        run("base n=2^20 M=8 K=16", build())
    if "fanout" in which:
        run("fanout=2 (M/4)", build(fanout=2))
    if "mailbox" in which:
        run("mailbox_cap=8 (K/2)", build(mailbox=8))
    if "n_half" in which:
        run("n=2^19 (N/2)", build(n=1 << 19))


if __name__ == "__main__":
    main()
