"""Random-access primitive costs on this chip (round 5).

The sparse-superstep design space is bounded by three numbers: long
1-op sort, short-axis sort, and random gather/scatter in its several
forms. Measure them all inside fori_loops with readback sync.
"""

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

from timewarp_tpu.utils import jaxconfig  # noqa: F401

import jax
import jax.numpy as jnp
from jax import lax

N = 1 << 20
A = 1 << 17
K = 16
REPS = 32


def loop(name, fn, *args):
    def rep(x, *rest):
        def body(i, x):
            return fn(x, i, *rest)
        return lax.fori_loop(jnp.int32(0), jnp.int32(REPS), body, x)
    f = jax.jit(rep)
    out = f(*args)
    int(jnp.asarray(jax.tree.leaves(out)[0]).reshape(-1)[0])
    t0 = time.perf_counter()
    out = f(*args)
    int(jnp.asarray(jax.tree.leaves(out)[0]).reshape(-1)[0])
    dt = (time.perf_counter() - t0) / REPS
    print(json.dumps({"op": name, "ms": round(dt * 1e3, 3)}))


def main():
    key = jax.random.PRNGKey(0)
    idx = jax.random.randint(key, (A,), 0, N, dtype=jnp.int32)
    x1 = jnp.arange(N, dtype=jnp.int32)
    x2 = jnp.tile(x1[None, :], (K, 1))          # [K, N]
    xa = jnp.arange(A, dtype=jnp.int32)

    # sorts
    loop("sort 1M 1-op", lambda x, i: lax.sort(x ^ i), x1)
    loop("sort [1024,1024] minor-axis 1-op",
         lambda x, i: lax.sort((x ^ i).reshape(1024, 1024),
                               dimension=1).reshape(N), x1)
    loop("sort [16,N] short-axis", lambda x, i: lax.sort(x ^ i,
                                                         dimension=0), x2)
    loop("sort 131k 1-op", lambda x, i: lax.sort(x ^ i), idx)
    loop("sort 131k 4-op 3-key",
         lambda x, i: lax.sort((x ^ i, x, x, x), dimension=0,
                               num_keys=3)[0], idx)
    loop("sort 1M 3-op 3-key",
         lambda x, i: lax.sort((x ^ i, x, x), dimension=0,
                               num_keys=3)[0], x1)

    # gathers
    loop("gather 1D 131k from 1M",
         lambda x, i: x.at[:A].set(x[(idx ^ i) % N]), x1)
    loop("gather 1D 131k sorted idx",
         lambda x, i: x.at[:A].set(x[jnp.clip(xa * 8 + i, 0, N - 1)]), x1)
    loop("take [16,N] axis1 131k (minor gather)",
         lambda x, i: x.at[:, :A].set(jnp.take(x, (idx ^ i) % N, axis=1)),
         x2)
    loop("per-row 16x 1D gather 131k",
         lambda x, i: x.at[0, :A].set(
             sum(x[k][(idx ^ i) % N] for k in range(K))), x2)

    # scatters
    loop("scatter 1D 131k into 1M",
         lambda x, i: x.at[(idx ^ i) % N].set(i, mode="drop"), x1)
    loop("scatter 2D [col,row] 131k into [16,N]",
         lambda x, i: x.at[(idx ^ i) % K, (idx ^ (i * 7)) % N].set(
             i, mode="drop"), x2)
    loop("scatter [16,A] cols into [16,N] (minor)",
         lambda x, i: x.at[:, (idx ^ i) % N].set(i, mode="drop"), x2)

    # elementwise reference
    loop("elementwise [16,N] 3 passes",
         lambda x, i: jnp.where(x > i, x - 1, x + 1) ^ (x >> 1), x2)


if __name__ == "__main__":
    main()
