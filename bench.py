"""Benchmark driver: delivered-messages/sec/chip across the baseline
workloads (BASELINE.json configs; targets in BASELINE.md).

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline",
"schema", "platform", "device_kind", "jax_version", "calib"}.
``vs_baseline`` is value / 1e8 (the north-star target; the reference
itself publishes no numbers — BASELINE.md); ``calib`` is a
frozen-kernel session fingerprint (see ``_calibrate``) so cross-round
artifacts separate tunnel variance from code changes; the environment
fields (``_env_fields``, schema-versioned) make CPU-only vs
chip-attached rounds distinguishable in the artifacts themselves.
Since BENCH_SCHEMA=2 every line also carries ``config``,
``config_key`` (the stable cross-run join key: config + requested
shape + platform), and ``git_sha``; ``--ledger DIR`` auto-appends
every emitted line to the persistent run ledger
(timewarp_tpu/obs/ledger.py — `timewarp-tpu ledger compare` is the
cross-run regression gate over it).
``gossip_100k_fused`` additionally runs the telemetry exactness +
overhead gate (``_telemetry_gate``: counters-mode digests bit-equal
to off, <= 5% traced-driver cost on chip) and reports
``telemetry_overhead_frac``.

Configs (select with TW_BENCH_CONFIG, default ``token_ring_dense``):

- ``token_ring_dense`` — the headline: dense token ring on the fused
  Pallas engine (one kernel per superstep, fused_ring.py), verified
  in-bench bit-for-bit against the XLA edge engine; the reference's
  north-star scenario at 1M nodes.
- ``token_ring_dense_xla`` — the same ring on the XLA edge engine
  (the pre-fusion baseline).
- ``token_ring_observer`` — the reference's *actual* token-ring shape
  (observer hub, dynamic destinations) on the general engine.
- ``gossip_100k`` — push-rumor broadcast, 100k nodes, lognormal
  latency quantized to a 1 ms grid (net/delays.py ``Quantize``:
  time-bucketed batching) on the general engine.
- ``praos_1m`` — Ouroboros-Praos slot-leader consensus at 1M stake
  nodes, general engine, quantized lognormal links.
- ``gossip_100k_fused`` / ``praos_1m_fused`` — the same two sparse
  workloads on the fused-sparse Pallas engine (fused_sparse.py, round
  6), gated in-bench by bit-exact state equality against the XLA
  general engine before the measured run counts.
- ``gossip_100k_b8`` / ``praos_1m_b4`` — the sparse workloads as
  multi-world FLEETS (engine.py ``batch=BatchSpec``, round 7): 8
  seed-swept gossip worlds / 4 link-swept praos worlds through one
  batched engine, reporting AGGREGATE delivered-msg/s/chip. Gated
  in-bench by the batch exactness law (world-b slice ≡ solo run,
  bit-for-bit) before the measured run counts.
- ``gossip_100k_insert`` / ``praos_1m_insert`` — the general engine
  with ``insert="pallas"`` (pallas_insert.py, round 12): the
  fire-compaction kernel replaces the sender-compaction sort +
  rung-width gathers and the in-tile insertion kernel replaces the
  mailbox scatters. Gated in-bench by bit-exact state equality
  against ``insert="xla"``; the JSON line additionally reports the
  isolated per-superstep insert-stage time for both strategies and
  the achieved-bytes / HBM-roofline fraction (``TW_HBM_GBPS``,
  default 270). On CPU the kernels run under the Pallas interpreter
  (``insert="interpret"`` — SMOKE-able; the stage timings then carry
  the cpu-platform caveat via the env fields).
- ``sweep_hetero`` — the fault-tolerant sweep service (sweep/,
  docs/sweeps.md) on a heterogeneous pack with one injected transient
  failure: aggregate delivered-msg/s THROUGH the service (journal +
  checkpoints included), gated by the sweep survival law (every
  streamed result ≡ its solo run, bit-for-bit).
- ``serve_gossip`` — emulation as a service (serve/,
  docs/serving.md): heterogeneous gossip configs admitted into
  open-bucket reserved slots (half mid-bucket) under a work-stealing
  curator, reporting served configs/sec and p50/p95
  submit→world_done latency, gated by the extended survival law
  (every streamed record ≡ its solo run, bit-for-bit).

Env knobs: TW_BENCH_CONFIG, TW_BENCH_NODES (config-default), and
TW_BENCH_STEPS (supersteps in the measured window). ``--reps K``
repeats the measured run K times and reports the median rate with
min/max in the JSON line — whole-run rates swing ±12% through the
tunnel (PERF_r05.md), so batched-vs-solo comparisons need it.

``python bench.py --smoke`` is the CI fast path: every config at tiny
N with all in-bench exactness gates on (fused ring, fused sparse AND
the batch exactness law), one JSON line per config — a kernel or
world-axis regression fails CI before a full bench round ever runs.
"""

import json
import os
import sys
import time

from timewarp_tpu.utils import jaxconfig  # noqa: F401

import jax


#: measured-window repetitions (set by --reps): the engine, its jit
#: compiles, and the in-bench exactness gates are paid ONCE per
#: config; only the measured window repeats. Virtual-time emulation
#: is deterministic, so `delivered` is identical across reps — only
#: wall-clock varies, which is exactly the tunnel variance --reps
#: exists to average out.
_REPS = 1
#: min/max rates of the last _measure (populated when _REPS > 1)
_SPREAD = {}
#: set by --smoke: measured numbers are meaningless at smoke scale,
#: so wall-clock gates (the telemetry overhead bound) report instead
#: of asserting there
_SMOKE = False

#: BENCH_*.json line schema version: bumped when the line's field
#: contract changes. v1 added the environment fields below — the
#: carried-forward CPU-vs-chip parity debt (ROADMAP) was invisible in
#: the artifacts themselves until the line said where it ran. v2 adds
#: ``config``, ``config_key`` (config name + requested shape +
#: platform — the stable cross-run join key), and ``git_sha`` (the
#: producing commit), so the run ledger (timewarp_tpu/obs/ledger.py)
#: joins trajectories unambiguously; v1 archives remain ingestable
#: (the ledger derives their key deterministically).
BENCH_SCHEMA = 2

#: resolved once per process (the sha cannot change mid-bench)
_GIT_SHA = None


def _git_sha():
    global _GIT_SHA
    if _GIT_SHA is None:
        from timewarp_tpu.obs.ledger import resolve_git_sha
        _GIT_SHA = resolve_git_sha(
            os.path.dirname(os.path.abspath(__file__)))
    return _GIT_SHA


def _config_key(cfg, n, steps):
    """The stable cross-run join key (BENCH_SCHEMA v2): config name +
    the REQUESTED shape (0/None = the config's default — itself a
    stable identity) + platform. Rates at different shapes or
    platforms are not comparable, so the key must separate them."""
    return (f"{cfg}|n{n or 'dflt'}|s{steps or 'dflt'}"
            f"|{jax.default_backend()}")


def _env_fields():
    """Environment provenance on every JSON line: cross-round
    trajectories (BENCH_r*.json) are only interpretable when each
    line names the platform/device/jax/commit that produced it."""
    dev = jax.devices()[0]
    return {"schema": BENCH_SCHEMA,
            "platform": jax.default_backend(),
            "device_kind": dev.device_kind,
            "jax_version": jax.__version__,
            "git_sha": _git_sha()}


#: (RunLedger, batch_id) when --ledger DIR was passed: every emitted
#: bench line auto-appends to the cross-run ledger (obs/ledger.py) —
#: running the bench IS recording it
_LEDGER = None


def _emit(line):
    """Print one bench JSON line AND (with --ledger) append it to the
    run ledger under this invocation's shared batch label."""
    print(json.dumps(line), flush=True)
    if _LEDGER is not None:
        _LEDGER[0].add_bench_line(line, batch=_LEDGER[1],
                                  source="bench.py")


def _measure(engine, steps, warm_steps=2):
    import numpy as np
    st = engine.init_state()
    st = jax.block_until_ready(st)

    def total(s):  # batched states carry per-world [B] counters
        return int(np.asarray(jax.device_get(s.delivered)).sum())

    # Warmup: compile the while_loop driver (first TPU compile 20-40 s).
    warm = engine.run_quiet(warm_steps, st)
    base = total(warm)  # force completion via host readback
    dts = []
    for _ in range(_REPS):
        t0 = time.perf_counter()
        fin = engine.run_quiet(steps, warm)
        delivered = total(fin) - base  # forces readback
        dts.append(time.perf_counter() - t0)
    import statistics
    dt = statistics.median(dts)
    _SPREAD.clear()
    if len(dts) > 1:
        _SPREAD.update(min=delivered / max(dts),
                       max=delivered / min(dts))
    return delivered, dt, fin


def _dense_ring(n):
    from timewarp_tpu.models.token_ring import token_ring
    from timewarp_tpu.net.delays import FixedDelay
    sc = token_ring(
        n, n_tokens=n, think_us=0, bootstrap_us=1_000,
        end_us=(1 << 50), with_observer=False, mailbox_cap=4)
    return sc, FixedDelay(500)


def bench_token_ring_dense(n, steps):
    """Dense ring, think_us=0, on the fused Pallas engine
    (interp/jax_engine/fused_ring.py): one kernel per superstep, each
    state byte touched once. In-bench verification: 12 supersteps on
    the general EdgeEngine must reproduce the fused state
    BIT-FOR-BIT before the measured run counts (the fused engine's
    exactness law, tests/test_fused_ring.py)."""
    import numpy as np
    from timewarp_tpu.interp.jax_engine.edge_engine import EdgeEngine
    from timewarp_tpu.interp.jax_engine.fused_ring import FusedRingEngine

    n = n or 1 << 20
    sc, link = _dense_ring(n)
    if n % 8192 != 0:
        # the fused kernel's pipeline block shape needs n % 8192 == 0
        # (fused_ring.py); smaller smoke shapes run the XLA engine
        return bench_token_ring_dense_xla(n, steps)
    engine = FusedRingEngine(sc, link, cap=2)
    ref = EdgeEngine(sc, link, cap=2)
    rs = ref.run_quiet(12)
    es = engine.to_edge_state(engine.run_quiet(12))
    for f in ("wake", "q_rel", "q_pay", "delivered", "overflow",
              "steps", "time"):
        assert np.array_equal(
            np.asarray(jax.device_get(getattr(rs, f))),
            np.asarray(jax.device_get(getattr(es, f)))), \
            f"fused engine diverged from EdgeEngine on {f}"
    for leaf in ("cnt", "val", "send_at"):
        assert np.array_equal(
            np.asarray(jax.device_get(rs.states[leaf])),
            np.asarray(jax.device_get(es.states[leaf]))), \
            f"fused engine diverged from EdgeEngine on state.{leaf}"
    # 8192 steps: the tunnel adds a ~120 ms round-trip to the final
    # readback (profiling/micro2_r05.py); at ~0.2 ms/superstep this
    # keeps the bias under 1%
    delivered, dt, fin = _measure(engine, steps or 8192)
    assert int(fin.overflow) == 0, "measured run left the parity regime"
    return (f"token-ring dense (fused pallas superstep) "
            f"delivered-messages/sec/chip @{n} nodes", delivered / dt)


def bench_token_ring_dense_xla(n, steps):
    """The same dense ring on the general XLA edge engine — the
    pre-fusion baseline, kept measurable."""
    from timewarp_tpu.interp.jax_engine.edge_engine import EdgeEngine

    n = n or 1 << 20
    sc, link = _dense_ring(n)
    engine = EdgeEngine(sc, link, cap=2)
    delivered, dt, fin = _measure(engine, steps or 2048)
    # in-bench proof the measured run is in the parity regime: per-edge
    # capacity legitimately diverges from the oracle under overflow
    # (edge_engine.py warns), so the headline number must come from a
    # run with none — mirroring bench_gossip_100k's quiescence asserts
    for counter in ("overflow", "misrouted", "unrouted", "bad_delay"):
        v = int(getattr(fin, counter))
        assert v == 0, f"measured run left the parity regime: {counter}={v}"
    return (f"token-ring dense (xla edge engine) "
            f"delivered-messages/sec/chip @{n} nodes", delivered / dt)


def bench_token_ring_observer(n, steps):
    """The reference example's real shape (examples/token-ring/Main.hs:
    104-208): every token hop also notifies an observer hub —
    dynamic destinations, general engine. Dense-token regime with
    think quantized so rings fire co-temporally."""
    from timewarp_tpu.interp.jax_engine.engine import JaxEngine
    from timewarp_tpu.models.token_ring import token_ring
    from timewarp_tpu.net.delays import FixedDelay

    n = n or (1 << 16)  # ring nodes; +1 observer
    sc = token_ring(
        n, n_tokens=n, think_us=1_000, bootstrap_us=1_000,
        end_us=(1 << 50), with_observer=True,
        mailbox_cap=8)
    engine = JaxEngine(sc, FixedDelay(500))
    delivered, dt, _ = _measure(engine, steps or 512)
    return (f"token-ring observer (general engine) "
            f"delivered-messages/sec/chip @{n} nodes", delivered / dt)


def _gossip_wave(n):
    """The gossip-wave workload: burst relays (all fanout peers in one
    firing — how a real node pushes over parallel connections) + an
    8 ms propagation floor licensing an 8-instant superstep window —
    the time-bucketed batching answer to the sparse broadcast ramp
    (JaxEngine.window)."""
    from timewarp_tpu.models.gossip import gossip, gossip_links
    from timewarp_tpu.net.delays import Quantize
    sc = gossip(n, fanout=8, think_us=2_000, burst=True,
                end_us=5_000_000, mailbox_cap=16)
    link = Quantize(gossip_links(median_us=20_000, sigma=0.6,
                                 floor_us=8_000), 1_000)
    return sc, link


def _assert_wave_done(engine, fin, n):
    """Genuine quiescence, not a window or deadline artifact: no
    events pending, the parity-regime counters are 0, and the
    epidemic covered the network up to the push-only miss floor (a
    node is missed with prob ~e^-fanout = e^-8 ≈ 3e-4; demanding
    literal 100% would assert against probability theory). Batched
    states are checked per WORLD — a truncated world must not hide
    behind the fleet aggregate."""
    import numpy as np
    from timewarp_tpu.core.scenario import NEVER
    # batched: per-world next-event times (vmap — _next_event mixes
    # the world-local epoch into the result); ALL worlds must quiesce
    nxt = jax.vmap(engine._next_event)(fin) \
        if getattr(engine, "batch", None) is not None \
        else engine._next_event(fin)
    assert int(np.asarray(jax.device_get(nxt)).min()) >= NEVER, \
        "broadcast did not quiesce inside the step budget"
    assert int(np.asarray(jax.device_get(fin.short_delay)).sum()) == 0, \
        "windowed run left the exact regime"
    assert int(np.asarray(jax.device_get(fin.route_drop)).sum()) == 0, \
        "routing dropped messages"
    hops = np.asarray(jax.device_get(fin.states["hop"]))
    for b, h in enumerate(hops.reshape(-1, hops.shape[-1])):
        missed = int((h < 0).sum())
        assert missed <= max(n // 500, 8), \
            f"wave truncated: {missed} nodes never infected (world {b})"


def _assert_batched_exact(batched, solo_factory, gate_steps=12):
    """The batch exactness law, in-bench (ISSUE 3 acceptance): for the
    first and last world, slicing the world out of a ``gate_steps``
    batched run must reproduce the solo engine's state BIT-FOR-BIT
    before any measured run counts (tests/test_world_batch.py is the
    CPU-side law; this runs it on the bench hardware)."""
    from timewarp_tpu.interp.jax_engine.batched import world_slice
    from timewarp_tpu.trace.events import assert_states_equal
    bs = batched.run_quiet(gate_steps)
    for b in (0, batched.batch.B - 1):
        ss = solo_factory(b).run_quiet(gate_steps)
        assert_states_equal(ss, world_slice(bs, b),
                            f"in-bench batch exactness gate, world {b}")


def _telemetry_gate(make_engine, steps=24, reps=3):
    """The telemetry exactness + overhead gate (obs/,
    docs/observability.md): ``telemetry="counters"`` must be
    bit-identical to ``"off"`` on the traced driver (states AND trace
    rows), and its throughput cost must stay <= 5%. The exactness
    half always asserts. The wall-clock half is strict (<= 5%) on a
    real chip-attached round, where the measured windows mean
    something; on CPU/smoke shapes the run-to-run noise dwarfs the
    budget, so the bound loosens to a 2x catastrophic-regression
    check and the measured ratio rides the JSON line for the record.
    Returns the overhead fraction (median-of-``reps`` per side)."""
    import statistics

    from timewarp_tpu.trace.events import (assert_states_equal,
                                           assert_traces_equal)
    off, on = make_engine("off"), make_engine("counters")
    f_off, tr_off = off.run(steps)
    f_on, tr_on = on.run(steps)
    assert_traces_equal(tr_off, tr_on, "telemetry-off",
                        "telemetry-counters")
    assert_states_equal(f_off, f_on, "telemetry exactness gate")

    def med(engine, state):
        walls = []
        for _ in range(reps):
            t0 = time.perf_counter()
            engine.run(steps, state=state)
            walls.append(time.perf_counter() - t0)
        return statistics.median(walls)

    w_off = med(off, f_off)       # warm states: compiles already paid
    w_on = med(on, f_on)
    overhead = w_on / w_off - 1.0
    strict = jax.default_backend() == "tpu" and not _SMOKE
    limit = 0.05 if strict else 1.0
    assert overhead <= limit, (
        f"telemetry='counters' costs {overhead:.1%} on the traced "
        f"driver — over the {limit:.0%} budget (obs/ "
        "zero-overhead contract)")
    return overhead


def _insert_mode():
    """The ``insert=`` value for this bench platform: the real kernels
    on TPU, the Pallas interpreter elsewhere (same semantics — the
    exactness gate still gates; the measured numbers then carry the
    cpu caveat in the env fields)."""
    return "pallas" if jax.default_backend() == "tpu" else "interpret"


def _assert_engines_exact(eng, ref, tag, gate_steps=12):
    """The ONE in-bench engine-pair exactness gate: ``ref`` must
    reproduce ``eng``'s EngineState BIT-FOR-BIT over the gate horizon
    before any measured run counts (the CPU-side laws live in
    tests/test_fused_sparse.py / tests/test_pallas_insert.py; this
    runs them on the bench hardware)."""
    from timewarp_tpu.trace.events import assert_states_equal
    es = eng.run_quiet(gate_steps)
    rs = ref.run_quiet(gate_steps)
    assert_states_equal(rs, es, tag)


def _assert_insert_exact(pallas, ref, gate_steps=12):
    """The insert= knob's gate: insert="xla" vs the pallas kernels."""
    _assert_engines_exact(pallas, ref, "in-bench pallas-insert gate",
                          gate_steps)


def _insert_stage_stats(engine, ref, reps=8):
    """Isolated per-superstep insert-stage timing + achieved-bytes /
    HBM-roofline fraction for the BENCH_SCHEMA JSON line (ISSUE 8
    satellite): a jitted call of each engine's own ``_insert_sorted``
    on one synthetic destination-sorted batch at the pallas stage's
    static width, against this scenario's empty mailbox. Bytes model:
    every mailbox plane read + written once, the resident batch read
    once — the kernel's streaming contract. The roofline constant is
    ``TW_HBM_GBPS`` (default 270 — the r5 dense-ring HBM floor,
    ~40 MB / 0.15 ms, PERF_r05.md). Caveats recorded with the number:
    each rep pays one host sync (~the tunnel RTT on a tunneled chip —
    treat sub-ms values as upper bounds; the floor-subtracted
    device-loop version is profiling/insert_stage_r06.py), and on CPU
    the fraction is not a roofline statement at all (the env fields
    say where the line ran)."""
    import statistics

    import jax.numpy as jnp
    import numpy as np

    sc = engine.scenario
    n, K, P = sc.n_nodes, sc.mailbox_cap, sc.payload_width
    S = engine._pallas_stage.S
    rng = np.random.RandomState(0)
    sd = jnp.asarray(np.sort(rng.randint(0, n, size=S))
                     .astype(np.int32))
    drel = jnp.asarray(rng.randint(1, 1 << 20, size=S)
                       .astype(np.int32))
    src = jnp.asarray(rng.randint(0, n, size=S).astype(np.int32))
    pay = tuple(jnp.asarray(rng.randint(0, 1 << 20, size=S)
                            .astype(np.int32)) for _ in range(P))
    ok = sd < n
    st = engine.init_state()
    if sc.commutative_inbox:
        # empty-mailbox free-slot table, in the engine's own dtype
        # rule (engine.py _superstep step 5: int8 when K fits)
        fr_dt = jnp.int8 if K <= 127 else jnp.int32
        free_rows = jnp.broadcast_to(
            jnp.arange(K, dtype=fr_dt)[:, None], (K, n))
        counts = None
    else:
        free_rows = None
        counts = jnp.zeros(n, jnp.int32)

    def timed(eng):
        # block on the FULL return (mb_rel, mb_src, mb_payload,
        # overflow): keeping only one output would let XLA dead-code
        # the src/payload scatters out of the xla leg while the
        # pallas_call always runs whole — a structurally biased
        # comparison
        f = jax.jit(lambda mb_rel, mb_src, mb_pay: eng._insert_sorted(
            mb_rel, mb_src, mb_pay, sd, ok, drel, src, pay,
            free_rows, counts))
        jax.block_until_ready(f(st.mb_rel, st.mb_src, st.mb_payload))
        walls = []
        for _ in range(max(3, reps)):
            t0 = time.perf_counter()
            jax.block_until_ready(
                f(st.mb_rel, st.mb_src, st.mb_payload))
            walls.append(time.perf_counter() - t0)
        return statistics.median(walls)

    t_pal, t_xla = timed(engine), timed(ref)
    planes = K * (1 + P + (1 if sc.inbox_src else 0))
    bytes_step = 2 * planes * n * 4 + (3 + P) * S * 4
    gbps = float(os.environ.get("TW_HBM_GBPS", "270"))
    return {
        "insert_stage_ms": round(t_pal * 1e3, 4),
        "insert_stage_xla_ms": round(t_xla * 1e3, 4),
        "insert_bytes_per_step": bytes_step,
        "insert_hbm_frac": round(bytes_step / t_pal / (gbps * 1e9), 4),
        "hbm_gbps_assumed": gbps,
        "insert_resolved": engine.insert_resolved,
    }


def _assert_fused_sparse_exact(fused, ref, gate_steps=12):
    """The fused-sparse engine's gate: the XLA general engine vs the
    fused kernel."""
    _assert_engines_exact(fused, ref, "in-bench fused-sparse gate",
                          gate_steps)


def bench_gossip_100k(n, steps):
    """One full broadcast wave, measured start to quiescence (the
    while_loop exits when the epidemic dies, so a large step budget
    costs nothing): whole-run average msg/s, ramp-up included."""
    from timewarp_tpu.interp.jax_engine.engine import JaxEngine

    n = n or 100_000
    sc, link = _gossip_wave(n)
    # window="auto" derives the widest exact window from the link's
    # declared 8 ms floor; adaptive sender-compacted routing (no
    # route_cap) sizes the insertion stage per superstep on-device —
    # no hand-measured capacity constants (VERDICT r4 item 6)
    engine = JaxEngine(sc, link, window="auto")
    delivered, dt, fin = _measure(engine, steps or (1 << 20))
    _assert_wave_done(engine, fin, n)
    return (f"gossip broadcast wave to quiescence (lognormal links) "
            f"delivered-messages/sec/chip @{n} nodes", delivered / dt)


def bench_gossip_100k_fused(n, steps):
    """The same wave on the fused-sparse Pallas engine
    (interp/jax_engine/fused_sparse.py): the compacted batch stays
    VMEM-resident through sample → bucket → hole-ranked insertion and
    the mailbox planes stream through the kernel once. Gated in-bench
    by bit-exact state equality against the XLA general engine."""
    from timewarp_tpu.interp.jax_engine.engine import JaxEngine
    from timewarp_tpu.interp.jax_engine.fused_sparse import \
        FusedSparseEngine

    n = n or 100_000
    sc, link = _gossip_wave(n)
    # max_batch bounds the VMEM-resident batch (1<<18 messages = 32k
    # burst senders/superstep); a wave peak beyond it lands in
    # route_drop and fails _assert_wave_done loudly — never a silently
    # wrong number
    engine = FusedSparseEngine(sc, link, window="auto",
                               max_batch=1 << 18)
    _assert_fused_sparse_exact(engine, JaxEngine(sc, link,
                                                 window="auto"))
    # the telemetry exactness + <= 5% overhead gate runs on THIS
    # config (the acceptance surface, ISSUE 7): counters-mode digests
    # must match off bit-for-bit before the measured run counts
    overhead = _telemetry_gate(lambda mode: FusedSparseEngine(
        sc, link, window="auto", max_batch=1 << 18, telemetry=mode,
        lint="off"))
    delivered, dt, fin = _measure(engine, steps or (1 << 20))
    _assert_wave_done(engine, fin, n)
    return (f"gossip broadcast wave to quiescence (fused-sparse "
            f"pallas) delivered-messages/sec/chip @{n} nodes",
            delivered / dt,
            {"telemetry_overhead_frac": round(overhead, 4)})


def bench_gossip_100k_insert(n, steps):
    """The gossip wave on the general engine with ``insert="pallas"``
    (pallas_insert.py): fire-compaction emits the compact fired batch
    in one streamed pass (no sender-compaction N-sort, no rung
    gathers) and the insertion kernel streams the mailbox planes
    through VMEM once. Gated in-bench by bit-exact state equality
    against ``insert="xla"``; reports the isolated insert-stage
    timings + roofline fraction. Default n is 2^17 (the kernels'
    1024-lane planes — 100k is not a multiple)."""
    from timewarp_tpu.interp.jax_engine.engine import JaxEngine

    n = n or (1 << 17)
    sc, link = _gossip_wave(n)
    # insert_cap bounds the VMEM-resident fire-compacted batch (the
    # fused engine's max_batch analog); a wave peak beyond it lands in
    # route_drop and fails _assert_wave_done loudly — never a silently
    # wrong number
    cap = min(1 << 18, n * sc.max_out)
    engine = JaxEngine(sc, link, window="auto", insert=_insert_mode(),
                       insert_cap=cap)
    ref = JaxEngine(sc, link, window="auto")
    _assert_insert_exact(engine, ref)
    extra = _insert_stage_stats(engine, ref)
    delivered, dt, fin = _measure(engine, steps or (1 << 20))
    _assert_wave_done(engine, fin, n)
    return (f"gossip broadcast wave to quiescence (pallas "
            f"insert) delivered-messages/sec/chip @{n} nodes",
            delivered / dt, extra)


def bench_praos_1m_insert(n, steps):
    """Praos on the general engine with ``insert="pallas"`` — the
    profiled hotspot (PERF_r05.md "where the remaining praos fat is")
    the kernels exist for. Same gates and stage stats as
    gossip_100k_insert."""
    from timewarp_tpu.interp.jax_engine.engine import JaxEngine

    n = n or 1 << 20
    sc, link = _praos_consensus(n)
    cap = min(1 << 17, n * sc.max_out)
    engine = JaxEngine(sc, link, window="auto", insert=_insert_mode(),
                       insert_cap=cap)
    ref = JaxEngine(sc, link, window="auto")
    _assert_insert_exact(engine, ref)
    extra = _insert_stage_stats(engine, ref)
    delivered, dt, fin = _measure(engine, steps or 256, warm_steps=16)
    assert int(fin.short_delay) == 0, \
        "windowed run left the exact regime"
    assert int(fin.route_drop) == 0, \
        "fire-compacted batch cap dropped messages — raise insert_cap"
    return (f"praos slot-leader consensus (pallas insert) "
            f"delivered-messages/sec/chip @{n} stake nodes",
            delivered / dt, extra)


def bench_gossip_100k_b8(n, steps):
    """The gossip wave as a FLEET: 8 seed-swept worlds through one
    batched engine (engine.py ``batch=BatchSpec`` — the world axis).
    The per-superstep fixed N-width costs (sender-compaction sort,
    mailbox passes) amortize across the batch, so AGGREGATE
    delivered-msg/s/chip should scale well past the solo gossip_100k
    rate (the replica-sweep workload, PERF_r05.md / ISSUE 3). Gated
    in-bench by the batch exactness law before the measured run."""
    from timewarp_tpu.interp.jax_engine.engine import (BatchSpec,
                                                       JaxEngine)

    n = n or 100_000
    B = 8
    sc, link = _gossip_wave(n)
    spec = BatchSpec(seeds=tuple(range(B)))
    engine = JaxEngine(sc, link, window="auto", batch=spec)
    # solo twins use the batched engine's RESOLVED window ("auto"
    # resolves against the min over world links) — the law compares
    # like with like
    _assert_batched_exact(engine, lambda b: JaxEngine(
        sc, spec.world_link(link, b), seed=spec.seeds[b],
        window=engine.window))
    delivered, dt, fin = _measure(engine, steps or (1 << 20))
    _assert_wave_done(engine, fin, n)
    stats = engine.last_run_stats or {}
    assert int(stats.get("compiles", 0)) == 0, (
        f"a MEASURED rep recompiled the warmed executable: {stats} — "
        "per-world identity rides as traced operands precisely so "
        "the fleet executable compiles once (batched.WorldIdentity)")
    return (f"gossip broadcast wave fleet (batched x{B}) aggregate "
            f"delivered-messages/sec/chip @{n} nodes", delivered / dt,
            {"engine_builds": 1,
             "compiles": int(stats.get("compiles", 0))})


def bench_gossip_100k_chaos(n, steps):
    """Monte-Carlo chaos study: 8 gossip worlds, 8 DISTINCT fault
    schedules (reset crashes + a mid-run partition + a degradation
    window per world — faults/), one batched engine. Steady-state
    mongering (not the one-shot wave) so re-infection after heals is
    guaranteed and convergence is a meaningful property. Gated
    in-bench by the chaos-fleet exactness law (world-b slice ≡ solo
    run with that world's schedule, bit-for-bit) AND a robustness
    property check (deliveries continue after every world's faults
    clear; every world converges to full infection) before the
    measured run counts. Reports aggregate delivered-msg/s/chip plus
    per-world route_drop / fault_dropped in the JSON line (the
    never-silent contract on the world axis)."""
    import numpy as np
    from timewarp_tpu.core.scenario import NEVER
    from timewarp_tpu.faults import (FaultFleet, FaultSchedule,
                                     LinkWindow, NodeCrash, Partition,
                                     eventually_delivered)
    from timewarp_tpu.interp.jax_engine.engine import (BatchSpec,
                                                       JaxEngine)
    from timewarp_tpu.models.gossip import gossip
    from timewarp_tpu.net.delays import Quantize, UniformDelay

    n = n or 100_000
    B = 8
    sc = gossip(n, fanout=1, think_us=1_000, gossip_interval=1_000,
                end_us=300_000, steady=True, mailbox_cap=8)
    link = Quantize(UniformDelay(500, 4_500), 1_000)
    half = n // 2
    heal_us = 0
    scheds = []
    for b in range(B):
        part_end = 70_000 + 2_000 * b
        crash_up = 60_000 + 5_000 * b
        # the LAST fault to clear in this world: the second crash
        # window runs to crash_up + 10 ms
        heal_us = max(heal_us, part_end, crash_up + 10_000)
        scheds.append(FaultSchedule((
            NodeCrash((7 * b + 3) % n, 20_000, crash_up,
                      reset_state=True),
            NodeCrash((11 * b + half + 5) % n, 30_000,
                      crash_up + 10_000),
            Partition((tuple(range(half)), tuple(range(half, n))),
                      25_000, part_end),
            LinkWindow(None, None, 80_000, 120_000,
                       scale=2.0 + 0.25 * b),
        )))
    fleet = FaultFleet(tuple(scheds))
    spec = BatchSpec(seeds=tuple(range(B)))
    engine = JaxEngine(sc, link, window="auto", batch=spec,
                       faults=fleet)
    # gate 1: the chaos-fleet exactness law on the bench hardware
    _assert_batched_exact(engine, lambda b: JaxEngine(
        sc, link, seed=spec.seeds[b], window=engine.window,
        faults=fleet.world_schedule(b)))
    # gate 2: robustness properties on a traced confirmation run —
    # traffic must still flow after every world's faults clear
    _, traces = engine.run(192)
    for b, tr in enumerate(traces):
        assert eventually_delivered(tr, heal_us), \
            f"world {b}: no deliveries after its faults healed"
    delivered, dt, fin = _measure(engine, steps or (1 << 20))
    # quiescence + parity-regime counters + convergence, per world
    nxt = jax.vmap(engine._next_event)(fin)
    assert int(np.asarray(jax.device_get(nxt)).min()) >= NEVER, \
        "chaos fleet did not quiesce inside the step budget"
    assert int(np.asarray(jax.device_get(fin.short_delay)).sum()) == 0, \
        "windowed run left the exact regime"
    route_drop = np.asarray(jax.device_get(fin.route_drop))
    fault_dropped = np.asarray(jax.device_get(fin.fault_dropped))
    assert int(route_drop.sum()) == 0, "routing dropped messages"
    hops = np.asarray(jax.device_get(fin.states["hop"]))
    for b in range(B):
        assert int(fault_dropped[b]) > 0, \
            f"world {b}: chaos schedule never bit (fault_dropped=0)"
        missed = int((hops[b] < 0).sum())
        assert missed <= max(n // 500, 8), \
            f"world {b} did not converge: {missed} nodes uninfected"
    extra = {"route_drop": route_drop.tolist(),
             "fault_dropped": fault_dropped.tolist()}
    return (f"gossip steady-state chaos fleet (batched x{B}, per-world "
            f"fault schedules) aggregate delivered-messages/sec/chip "
            f"@{n} nodes", delivered / dt, extra)


def bench_sweep_hetero(n, steps):
    """The fault-tolerant sweep service (sweep/, docs/sweeps.md) on a
    heterogeneous pack: token-ring seed+link sweeps (one world
    faulted, budgets differing) plus windowed burst-gossip worlds,
    shape-bucketed onto batched engines and run under the supervision
    loop with ONE injected transient failure (the retry path is
    exercised every time, not just in tests). Gated by the sweep
    survival law before the number counts: every streamed per-world
    result record — chained trace digest + never-silent counters —
    must be bit-identical to the solo run of that config. Runs the
    SAME pack twice — ``--pack first-fit`` and ``--pack predicted``
    (timewarp_tpu/pack/, docs/sweeps.md "Predictive packing") — and
    gates the packed leg in-bench: strictly better
    ``budget_efficiency``, no worse ``pad_waste_frac``, identical
    engine-build count, survival law on both legs, and one journaled
    ``pack_decision`` per bucket (first-fit journals none). Reports
    the packed leg's aggregate delivered-msg/s through the service
    (journal + atomic checkpoints included — service throughput, not
    bare engine throughput) with both legs' packing rollups on the
    line."""
    import shutil
    import tempfile

    from timewarp_tpu.sweep import SweepPack, SweepService, solo_result

    n = n or 4096
    steps = steps or 2000
    # the half-budget world's budget is the largest pow2 <= steps/2:
    # a pow2 budget drains on exact scan rungs, so the packing gate
    # below measures PACKING (which worlds share a bucket), not the
    # pow2 rung residue of an arbitrary odd budget
    half = max(8, 1 << (max(1, steps // 2).bit_length() - 1))
    ring = {"nodes": n, "n_tokens": max(4, n // 64), "think_us": 2000,
            "end_us": 1 << 40, "mailbox_cap": 8}
    gossip = {"nodes": n, "fanout": 4, "burst": True,
              "end_us": 400_000, "mailbox_cap": 16, "think_us": 700}
    pack = SweepPack.from_json([
        {"id": "ring-s0", "scenario": "token-ring", "params": ring,
         "link": "uniform:1000:5000", "seed": 0, "budget": steps},
        {"id": "ring-s1", "scenario": "token-ring", "params": ring,
         "link": "uniform:2000:7000", "seed": 1, "budget": half},
        {"id": "ring-chaos", "scenario": "token-ring", "params": ring,
         "link": "uniform:1000:5000", "seed": 2, "budget": steps,
         "faults": "crash:3:5ms:40ms:reset; partition:0-1|2-3:10ms:30ms"},
        {"id": "gos-s0", "scenario": "gossip", "params": gossip,
         "link": "quantize:1000:uniform:3000:9000", "seed": 3,
         "window": "auto", "budget": steps},
        {"id": "gos-s1", "scenario": "gossip", "params": gossip,
         "link": "quantize:1000:uniform:4000:8000", "seed": 4,
         "window": "auto", "budget": steps},
    ])
    from timewarp_tpu.sweep.journal import SweepJournal, util_rollup

    def leg(pack_mode):
        d = tempfile.mkdtemp(prefix="tw_sweep_bench_")
        try:
            t0 = time.perf_counter()
            # max_bucket=2 makes the packing decision REAL at this
            # pack's scale: the three token-ring worlds (budgets
            # steps, steps/2, steps in pack order) cannot share one
            # bucket, so first-fit pairs a half-budget world with a
            # full-budget one while predicted re-sorts the group
            # best-fit-decreasing and pairs like with like
            # pow2 chunk for the same reason as the pow2 half budget
            chunk = max(64, 1 << (max(1, steps // 8).bit_length() - 1))
            svc = SweepService(pack, d, chunk=chunk,
                               lint="off", inject="fail:2",
                               max_bucket=2, pack_mode=pack_mode)
            report = svc.run()
            dt = time.perf_counter() - t0
            assert report.ok, f"sweep failed: {report.to_json()}"
            assert report.retries >= 1, \
                "the injected transient failure never exercised " \
                "the retry path"
            # the survival law, world by world, on BOTH legs: packing
            # is pure throughput — streamed results must be
            # bit-identical to solo regardless of bucketing (the gate
            # deliberately costs a second pass)
            for rid, res in report.done.items():
                want = solo_result(pack.by_id(rid), lint="off")
                assert want == res, (
                    f"sweep survival law violated for {rid} "
                    f"({pack_mode}):\n"
                    f"  solo:     {want}\n  streamed: {res}")
            scan = SweepJournal(d).scan()
            roll = util_rollup(scan.util)
            builds = sum(int(u.get("engine_builds", 0))
                         for u in scan.util.values())
            return {"report": report, "dt": dt, "roll": roll,
                    "builds": builds,
                    "decisions": len(scan.pack_decisions),
                    "delivered": sum(r["delivered"]
                                     for r in report.done.values())}
        finally:
            shutil.rmtree(d, ignore_errors=True)

    ff = leg("first-fit")
    pr = leg("predicted")
    # the in-bench packing gate (docs/sweeps.md "Predictive
    # packing"): on the same pack, the packed leg must strictly win
    # budget efficiency, never lose pad waste, and build exactly as
    # many engines — packing changes WHERE worlds run, never what
    # they compute or how often anything compiles
    assert pr["roll"]["budget_efficiency"] \
            > ff["roll"]["budget_efficiency"], (
        f"predicted packing did not beat first-fit: "
        f"budget_efficiency {pr['roll']} vs {ff['roll']}")
    assert pr["roll"]["pad_waste_frac"] \
            <= ff["roll"]["pad_waste_frac"] + 1e-9, (
        f"predicted packing grew pad waste: {pr['roll']} "
        f"vs {ff['roll']}")
    assert pr["builds"] == ff["builds"], (
        f"packing changed engine build count: {pr['builds']} "
        f"predicted vs {ff['builds']} first-fit")
    assert ff["decisions"] == 0, \
        "first-fit journaled pack_decision records (the first-fit " \
        "plan is a pure function of the pack — nothing to journal)"
    assert pr["decisions"] == pr["report"].buckets, (
        f"predicted leg journaled {pr['decisions']} pack_decision "
        f"records for {pr['report'].buckets} buckets — the plan "
        "must be journaled one record per bucket before any starts")
    extra = {"worlds": pr["report"].total,
             "buckets": pr["report"].buckets,
             "retries": pr["report"].retries,
             "splits": pr["report"].splits,
             # the packing rollups (sweep/journal.py util_rollup) —
             # promoted to the ledger index so `ledger compare`
             # rate-gates packing regressions across rounds
             "budget_efficiency": pr["roll"]["budget_efficiency"],
             "pad_waste_frac": pr["roll"]["pad_waste_frac"],
             "first_fit_budget_efficiency":
                 ff["roll"]["budget_efficiency"],
             "first_fit_pad_waste_frac":
                 ff["roll"]["pad_waste_frac"],
             "pack_decisions": pr["decisions"]}
    return (f"heterogeneous sweep service (retry + stream + survival "
            f"law + predictive packing gate) aggregate "
            f"delivered-messages/sec @{n} nodes",
            pr["delivered"] / pr["dt"], extra)


def _bursty_gossip(n):
    """Density-varying workload for the dispatch-controller bench
    (dispatch/, docs/dispatch.md): burst-wave gossip with a long think
    incubation — quiet phases between fan-out storm generations — over
    an 8 ms-floor link, plus a mid-run degradation window that
    undercuts the floor to 2 ms. The scenario where no single static
    window can win: a static engine must validate against the
    schedule-wide degraded floor (2 ms) for the WHOLE run, while the
    controller runs the 8 ms bound and the per-superstep device clamp
    (faults/apply.window_floor) narrows exactly the supersteps the
    degradation window overlaps."""
    from timewarp_tpu.faults import FaultSchedule, LinkWindow
    from timewarp_tpu.models.gossip import gossip, gossip_links
    from timewarp_tpu.net.delays import Quantize
    sc = gossip(n, fanout=8, think_us=40_000, burst=True,
                end_us=5_000_000, mailbox_cap=16)
    link = Quantize(gossip_links(median_us=20_000, sigma=0.6,
                                 floor_us=8_000), 1_000)
    faults = FaultSchedule((LinkWindow(None, None, 100_000, 200_000,
                                       scale=0.25),))
    return sc, link, faults


def bench_gossip_100k_auto(n, steps):
    """The bursty gossip wave under the online dispatch controller
    (run_controlled: telemetry-driven window/rung/chunk adaptation,
    zero retrace). Gated in-bench by the REPLAY LAW — a second engine
    re-executing the emitted decision trace must reproduce the
    digests bit-for-bit — and by a deterministic structural win:
    fewer supersteps than the best static window (which the
    degradation window forces down to the schedule-wide floor).
    Reports ``controller_gain_frac`` vs the best single static
    config; the wall-clock half is asserted > 0 on full rounds only
    (smoke-scale CPU noise dwarfs it — the superstep win asserts
    everywhere)."""
    import numpy as np
    from timewarp_tpu.dispatch import DecisionTrace, DispatchController
    from timewarp_tpu.interp.jax_engine.engine import JaxEngine
    from timewarp_tpu.sweep.spec import DIGEST_ZERO, chain_digest
    from timewarp_tpu.trace.events import assert_states_equal

    n = n or 100_000
    steps = steps or (1 << 14)
    sc, link, faults = _bursty_gossip(n)
    eng = JaxEngine(sc, link, window="auto", faults=faults,
                    telemetry="counters", lint="off",
                    controller=DispatchController(chunk=16,
                                                  chunk_max=64))
    eng.run_controlled(steps)  # warmup: compiles + the decision trace
    decs = eng.last_run_decisions
    t0 = time.perf_counter()
    fin, tr = eng.run_controlled(steps)  # decisions replayed from made
    wall_auto = time.perf_counter() - t0
    delivered = int(np.asarray(jax.device_get(fin.delivered)).sum())
    # gate 1: the replay law — a fresh engine re-executing the
    # decision trace must match digests bit-for-bit
    rep = JaxEngine(sc, link, window="auto", faults=faults, lint="off",
                    controller=DispatchController(
                        mode="replay", replay=DecisionTrace.of(decs)))
    rfin, rtr = rep.run_controlled(steps)
    assert chain_digest(DIGEST_ZERO, tr) == chain_digest(DIGEST_ZERO,
                                                         rtr), \
        "controller run's digests diverge from its decision-trace " \
        "replay (the replay law)"
    assert_states_equal(fin, rfin, "controller replay law (bench)")
    _assert_wave_done(eng, fin, n)
    # best static config: the widest legal static window (the
    # schedule-wide degraded floor — construction refuses anything
    # wider under this schedule) and the classic window=1 engine.
    # Each gets its BEST driver — run_quiet's while_loop exits at
    # quiescence with no trace/telemetry work compiled in — so the
    # controller's chunked traced driver competes against the
    # strongest static baseline, not a strawman
    best_rate, best_name, static_steps = 0.0, "", None
    for name, w in (("static-auto", "auto"), ("window-1", 1)):
        st_eng = JaxEngine(sc, link, window=w, faults=faults,
                           lint="off")
        st_eng.run_quiet(steps)  # warmup compile
        t0 = time.perf_counter()
        sfin = st_eng.run_quiet(steps)
        dt = time.perf_counter() - t0
        sdel = int(np.asarray(jax.device_get(sfin.delivered)).sum())
        assert sdel == delivered, \
            f"static {name} delivered {sdel} != controller {delivered}"
        if sdel / dt > best_rate:
            best_rate, best_name = sdel / dt, name
        if name == "static-auto":
            static_steps = int(np.asarray(
                jax.device_get(sfin.steps)).max())
    # gate 2: deterministic structural win — the controller's wide
    # windows outside the degradation slice coalesce more instants
    assert len(tr) < static_steps, \
        f"controller ran {len(tr)} supersteps vs static-auto's " \
        f"{static_steps} — the window adaptation never bit"
    gain = delivered / wall_auto / best_rate - 1.0
    if not _SMOKE:
        assert gain > 0, \
            f"controller_gain_frac={gain:.4f} <= 0 vs {best_name}"
    extra = {"controller_gain_frac": round(gain, 4),
             "best_static": best_name,
             "supersteps_auto": len(tr),
             "supersteps_static": static_steps,
             "decisions": len(decs),
             "decision_windows": sorted({d.window_us for d in decs})}
    return (f"bursty gossip wave under the dispatch controller "
            f"(auto window/rung/chunk) delivered-messages/sec/chip "
            f"@{n} nodes", delivered / wall_auto, extra)


def bench_gossip_100k_spec(n, steps):
    """Optimistic time-warp execution on a long-tail link
    (speculate/, docs/speculation.md): bursty gossip over
    ``quantize:500:pareto:4000:1.2`` — Pareto delays supported on
    [4 ms, ∞) with a heavy upper tail, DECLARED floor the 500 µs
    quantize grid. The provable window serializes supersteps at
    500 µs while no sample ever lands below 4 ms; ``speculate="auto"``
    ladders the window into that gap, rolling back when a probe
    overshoots the distribution's real support. Gated in-bench by the
    SPECULATION EQUIVALENCE LAW (canonical surface — granularity-
    invariant trace aggregates + final-state sha — bit-identical to
    the conservative run, speculate/equiv.py) and by the
    deterministic structural win (strictly fewer supersteps).
    Reports ``speculation_gain_frac`` (supersteps saved) with the
    honest misspeculation ledger — rollback count and rate — on the
    BENCH_SCHEMA line; the wall-clock half is asserted > 0 on full
    rounds only (smoke-scale CPU noise dwarfs it, the
    gossip_100k_auto precedent)."""
    import numpy as np
    from timewarp_tpu.interp.jax_engine.engine import JaxEngine
    from timewarp_tpu.models.gossip import gossip
    from timewarp_tpu.net.delays import ParetoDelay, Quantize
    from timewarp_tpu.speculate import (assert_spec_equiv,
                                        canonical_rows)

    n = n or 100_000
    steps = steps or (1 << 14)
    sc = gossip(n, fanout=8, think_us=40_000, burst=True,
                end_us=5_000_000, mailbox_cap=16)
    link = Quantize(ParetoDelay(4_000, 1.2), 500)

    spec = JaxEngine(sc, link, window="auto", lint="off",
                     speculate="auto")
    spec.run_speculative(steps, chunk=64)   # warmup: compiles
    t0 = time.perf_counter()
    sfin, strc = spec.run_speculative(steps, chunk=64)
    wall_spec = time.perf_counter() - t0
    si = spec.last_run_speculation
    delivered = int(np.asarray(jax.device_get(sfin.delivered)).sum())
    _assert_wave_done(spec, sfin, n)

    # the conservative twin: same config, the widest PROVABLE static
    # window ("auto" = the declared floor). Traced run for the
    # equivalence gate + superstep count; run_quiet for the timing
    # baseline (its best driver — no strawman)
    cons = JaxEngine(sc, link, window="auto", lint="off")
    cfin, ctrc = cons.run(steps)
    _assert_wave_done(cons, cfin, n)
    assert int(np.asarray(jax.device_get(cfin.overflow)).sum()) == 0, \
        "overflow > 0: outside the windowed-exactness regime"
    # gate 1: the speculation equivalence law, bit-for-bit
    assert_spec_equiv(canonical_rows(cfin, ctrc),
                      canonical_rows(sfin, strc),
                      "gossip_100k_spec in-bench gate")
    cons.run_quiet(steps)                   # warmup the quiet driver
    t0 = time.perf_counter()
    cons.run_quiet(steps)
    wall_cons = time.perf_counter() - t0
    # gate 2: deterministic structural win — wide committed windows
    # coalesce instants the conservative floor serializes
    assert len(strc) < len(ctrc), \
        f"speculation ran {len(strc)} supersteps vs the " \
        f"conservative {len(ctrc)} — the window never widened"
    gain = 1.0 - len(strc) / len(ctrc)
    wall_gain = wall_cons / wall_spec - 1.0
    if not _SMOKE:
        assert wall_gain > 0, \
            f"speculation wall gain {wall_gain:.4f} <= 0"
    chunks = int(si["chunks"])
    rb = int(si["rollbacks"])
    extra = {"speculation_gain_frac": round(gain, 4),
             "wall_gain_frac": round(wall_gain, 4),
             "rollbacks": rb,
             "rollback_rate": round(rb / max(chunks + rb, 1), 4),
             "supersteps_spec": len(strc),
             "supersteps_conservative": len(ctrc),
             "windows": si["windows"],
             "floor_us": si["floor_us"]}
    return (f"bursty gossip on a heavy-tail pareto link under "
            f"optimistic time-warp execution (speculative windows + "
            f"causality rollback) delivered-messages/sec/chip "
            f"@{n} nodes", delivered / wall_spec, extra)


def bench_sweep_hetero_auto(n, steps):
    """The heterogeneous sweep with the windowed gossip worlds under
    ``controller: auto`` (sweep/: per-bucket decisions journaled
    before each chunk). Gated by the controller form of the sweep
    survival law: every streamed result must be bit-identical to the
    solo run REPLAYING the bucket's journaled decision chain — plus
    the plain law for the controller-off worlds."""
    import shutil
    import tempfile

    from timewarp_tpu.sweep import SweepPack, SweepService, solo_result

    n = n or 4096
    steps = steps or 2000
    ring = {"nodes": n, "n_tokens": max(4, n // 64), "think_us": 2000,
            "end_us": 1 << 40, "mailbox_cap": 8}
    gossip = {"nodes": n, "fanout": 4, "burst": True,
              "end_us": 400_000, "mailbox_cap": 16, "think_us": 700}
    pack = SweepPack.from_json([
        {"id": "ring-s0", "scenario": "token-ring", "params": ring,
         "link": "uniform:1000:5000", "seed": 0, "budget": steps},
        {"id": "gos-a0", "scenario": "gossip", "params": gossip,
         "link": "quantize:1000:uniform:3000:9000", "seed": 3,
         "window": "auto", "budget": steps, "controller": "auto"},
        {"id": "gos-a1", "scenario": "gossip", "params": gossip,
         "link": "quantize:1000:uniform:3000:9000", "seed": 4,
         "window": "auto", "budget": max(steps // 2, 8),
         "controller": "auto"},
        {"id": "gos-a2", "scenario": "gossip", "params": gossip,
         "link": "quantize:1000:uniform:4000:8000", "seed": 5,
         "window": "auto", "budget": steps, "controller": "auto"},
    ])
    d = tempfile.mkdtemp(prefix="tw_sweep_auto_")
    try:
        t0 = time.perf_counter()
        svc = SweepService(pack, d, chunk=max(16, steps // 16),
                           lint="off", inject="fail:2")
        report = svc.run()
        dt = time.perf_counter() - t0
        assert report.ok, f"sweep failed: {report.to_json()}"
        assert report.retries >= 1, \
            "the injected transient failure never exercised the retry"
        scan = svc.journal.scan()
        n_dec = sum(len(v) for v in scan.decisions.values())
        assert n_dec > 0, "controller bucket journaled no decisions"
        for rid, res in report.done.items():
            cfg = pack.by_id(rid)
            decs = svc.decisions_for_world(rid, scan) \
                if cfg.controller == "auto" else None
            want = solo_result(cfg, lint="off", decisions=decs)
            assert want == res, (
                f"controller sweep survival law violated for {rid}:\n"
                f"  solo:     {want}\n  streamed: {res}")
        delivered = sum(r["delivered"] for r in report.done.values())
        extra = {"worlds": report.total,
                 "controller_worlds": sum(
                     1 for c in pack.configs if c.controller == "auto"),
                 "decisions_journaled": n_dec,
                 "retries": report.retries}
    finally:
        shutil.rmtree(d, ignore_errors=True)
    return (f"heterogeneous sweep service with per-bucket dispatch "
            f"controller (decisions journaled + replay-verified) "
            f"aggregate delivered-messages/sec @{n} nodes",
            delivered / dt, extra)


def bench_search_gossip(n, steps):
    """Adversarial chaos search (timewarp_tpu/search/, docs/
    search.md): a seeded ChaosSearch campaign over fault-schedule
    space on burst gossip — generations of candidate schedules
    evaluated as shape-shared batched fleets, counterfactual forking
    (suffix continuations from a digest-verified mid-run snapshot),
    delta-minimization, and the repro artifact. Three gates before
    the number counts: the campaign must FIND a property violation
    (eventually-delivered — the rumor can be starved), the minimized
    repro must re-fail the property on a from-scratch solo
    evaluation (the replayability gate), and at least one fork must
    have saved real supersteps (``fork_saving_frac > 0``). Reports
    world evaluations/sec through the whole campaign (compiles,
    forks, minimization, and journaling included — this is search
    throughput, not bare engine throughput)."""
    import shutil
    import tempfile

    from timewarp_tpu.search import ChaosSearch
    from timewarp_tpu.search.objectives import rejudge_repro
    from timewarp_tpu.sweep.spec import RunConfig

    n = n or 64
    steps = steps or 300
    params = {"nodes": n, "fanout": 2, "end_us": 120_000,
              "burst": True, "think_us": 5000, "mailbox_cap": 16}
    base = RunConfig(run_id="search-base", family="gossip",
                     params=tuple(sorted(params.items())),
                     link="uniform:1000:5000", seed=0, window="auto",
                     budget=steps)
    d = tempfile.mkdtemp(prefix="tw_search_bench_")
    try:
        t0 = time.perf_counter()
        campaign = ChaosSearch(base=base,
                               objective="eventually-delivered",
                               population=8, generations=6, seed=2,
                               fork_k=2, journal_dir=d)
        result = campaign.run()
        dt = time.perf_counter() - t0
        assert result.found, (
            f"the seeded campaign failed to rediscover a violating "
            f"schedule: {result.to_json()}")
        assert result.fork["saving_frac"] > 0, (
            "counterfactual forking never saved a superstep: "
            f"{result.fork}")
        # the replayability gate: the emitted repro re-fails the
        # property on a fresh solo evaluation (the one shared
        # artifact-replay helper — search/objectives.rejudge_repro)
        rec = result.repro
        obj, violated, _ = rejudge_repro(rec)
        assert violated, (
            f"minimized repro {rec['faults']!r} does not re-fail "
            f"{obj.name}")
        evals = (result.evaluations + result.fork["fork_worlds"]
                 + result.fork["confirmations"] + 1)
        extra = {"evaluations": evals,
                 "generations": len(result.generations),
                 "found": True,
                 "fork_saving_frac": result.fork["saving_frac"],
                 "forks": result.fork["forks"],
                 "minimized": result.minimized,
                 "minimized_events": rec["events"]}
    finally:
        shutil.rmtree(d, ignore_errors=True)
    return (f"adversarial chaos search (campaign + counterfactual "
            f"fork + minimize + repro re-fail gate) world "
            f"evaluations/sec @{n} nodes", evals / dt, extra)


def bench_praos_1m_b4(n, steps):
    """Praos as a 4-world fleet sweeping BOTH seed and link model per
    world (lognormal median 18/20/22/24 ms — a Monte-Carlo link study
    in one engine, via BatchSpec.link_params), exactness-gated like
    the gossip fleet; aggregate delivered-msg/s/chip."""
    import numpy as np
    from timewarp_tpu.interp.jax_engine.engine import (BatchSpec,
                                                       JaxEngine)

    n = n or 1 << 20
    B = 4
    sc, link = _praos_consensus(n)
    spec = BatchSpec(
        seeds=tuple(range(B)),
        link_params={"inner.median_us": [18_000, 20_000,
                                         22_000, 24_000]})
    engine = JaxEngine(sc, link, window="auto", batch=spec)
    _assert_batched_exact(engine, lambda b: JaxEngine(
        sc, spec.world_link(link, b), seed=spec.seeds[b],
        window=engine.window))
    delivered, dt, fin = _measure(engine, steps or 256, warm_steps=16)
    assert int(np.asarray(jax.device_get(fin.short_delay)).sum()) == 0, \
        "windowed run left the exact regime"
    assert int(np.asarray(jax.device_get(fin.route_drop)).sum()) == 0, \
        "adaptive routing dropped messages"
    return (f"praos slot-leader consensus fleet (batched x{B}, link "
            f"sweep) aggregate delivered-messages/sec/chip "
            f"@{n} stake nodes", delivered / dt)


def bench_gossip_steady_1m(n, steps):
    """Rumor-mongering steady state: every infected node relays to one
    pseudo-random peer per 1 ms round — the dense dynamic-destination
    regime of the general engine (1M messages per superstep at 1M
    nodes, every one through the all-destination routing path)."""
    from timewarp_tpu.interp.jax_engine.engine import JaxEngine
    from timewarp_tpu.models.gossip import gossip
    from timewarp_tpu.net.delays import Quantize, UniformDelay

    n = n or 1 << 20
    sc = gossip(n, fanout=1, think_us=1_000, gossip_interval=1_000,
                end_us=(1 << 50), steady=True, mailbox_cap=8)
    link = Quantize(UniformDelay(500, 4_500), 1_000)
    engine = JaxEngine(sc, link)
    # warm through the infection ramp-up so the measured window is the
    # steady state (seed node infects ~2^k nodes by round k)
    delivered, dt, _ = _measure(engine, steps or 256, warm_steps=64)
    return (f"gossip steady-state (rumor mongering) "
            f"delivered-messages/sec/chip @{n} nodes", delivered / dt)


def _praos_consensus(n):
    """The praos workload: burst diffusion (a fresh tip floods all
    fanout peers in one firing) + 8 ms propagation floor + 8 ms
    window — adoption instants spread by lognormal delays batch 8
    grid instants per superstep (exact — engine.py JaxEngine.window).
    The 150 ms delay cap bounds the straggler tail (a 60 s praos
    relay is not a network, it is an outage)."""
    from timewarp_tpu.models.praos import praos
    from timewarp_tpu.net.delays import LogNormalDelay, Quantize
    sc = praos(n, slot_us=1_000_000, n_slots=1 << 30,
               leader_prob=4.0 / n, fanout=8, burst=True,
               mailbox_cap=16)
    link = Quantize(LogNormalDelay(20_000, 0.6, cap_us=150_000,
                                   floor_us=8_000), 1_000)
    return sc, link


def bench_praos_1m(n, steps):
    from timewarp_tpu.interp.jax_engine.engine import JaxEngine

    n = n or 1 << 20
    sc, link = _praos_consensus(n)
    # window="auto" (link's 8 ms floor) + adaptive routing: no
    # hand-measured capacity constants (VERDICT r4 item 6)
    engine = JaxEngine(sc, link, window="auto")
    delivered, dt, fin = _measure(engine, steps or 256, warm_steps=16)
    assert int(fin.short_delay) == 0, "windowed run left the exact regime"
    # invariant, not a tuning-knob guard (see bench_gossip_100k)
    assert int(fin.route_drop) == 0, "adaptive routing dropped messages"
    return (f"praos slot-leader consensus "
            f"delivered-messages/sec/chip @{n} stake nodes",
            delivered / dt)


def bench_praos_1m_fused(n, steps):
    """Praos on the fused-sparse Pallas engine, exactness-gated
    against the XLA general engine in-bench (see
    bench_gossip_100k_fused)."""
    from timewarp_tpu.interp.jax_engine.engine import JaxEngine
    from timewarp_tpu.interp.jax_engine.fused_sparse import \
        FusedSparseEngine

    n = n or 1 << 20
    sc, link = _praos_consensus(n)
    engine = FusedSparseEngine(sc, link, window="auto",
                               max_batch=1 << 17)
    _assert_fused_sparse_exact(engine, JaxEngine(sc, link,
                                                 window="auto"))
    delivered, dt, fin = _measure(engine, steps or 256, warm_steps=16)
    assert int(fin.short_delay) == 0, "windowed run left the exact regime"
    assert int(fin.route_drop) == 0, \
        "fused batch cap dropped messages — raise max_batch"
    return (f"praos slot-leader consensus (fused-sparse pallas) "
            f"delivered-messages/sec/chip @{n} stake nodes",
            delivered / dt)


def _verify_detection_gate(make_engine, budget=64, chunk=8):
    """The detection law, in-bench (integrity/, ISSUE 10 acceptance):
    one seeded flip injected between chunks of a digest-mode run must
    be DETECTED (>= 1 rollback) and the recovered run bit-identical —
    states, traces, digest chain — to a clean run. Runs before any
    measured number counts, like every other in-bench gate."""
    from timewarp_tpu.integrity import FlipInjector
    from timewarp_tpu.trace.events import (assert_states_equal,
                                           assert_traces_equal)
    clean = make_engine("digest")
    fc, tc = clean.run_verified(budget, chunk=chunk)
    injected = make_engine("digest")
    inj = FlipInjector("flip:7:2")
    fi, ti = injected.run_verified(budget, chunk=chunk, inject=inj)
    assert inj.fired, "flip never fired (fewer than 2 chunks ran)"
    assert injected.last_run_integrity["rollbacks"] >= 1, \
        "injected flip went UNDETECTED (the detection law is broken)"
    assert_traces_equal(tc, ti, "clean", "recovered")
    assert_states_equal(fc, fi, "in-bench detection-law gate")
    assert clean.last_run_stats["digest_chain"] \
        == injected.last_run_stats["digest_chain"], \
        "recovered digest chain diverged from the clean run's"


def bench_gossip_100k_verify(n, steps):
    """Self-verifying execution (integrity/, docs/integrity.md): the
    gossip wave through the verified chunked driver under every
    verify mode, reporting ``verify_overhead_frac`` per mode vs the
    same driver with verify off. Gated in-bench by the detection law
    (one injected flip -> detected + bit-exact recovery) and by the
    digest-mode overhead budget: <= 10% strict on a chip-attached
    round; on CPU/smoke the run-to-run noise dwarfs the budget, so
    the bound loosens to a 2x catastrophic-regression check and the
    measured fractions ride the JSON line for the record (the same
    convention as the telemetry gate)."""
    import statistics

    from timewarp_tpu.interp.jax_engine.engine import JaxEngine

    n = n or 100_000
    sc, link = _gossip_wave(n)

    def make(mode):
        return JaxEngine(sc, link, window="auto", lint="off",
                         verify=mode)

    _verify_detection_gate(make)
    budget = steps or (1 << 20)
    chunk = 256

    def med(mode, reps=2):
        eng = make(mode)
        eng.run_verified(budget, chunk=chunk)   # warm the compiles
        walls = []
        for _ in range(reps):
            t0 = time.perf_counter()
            fin, _tr = eng.run_verified(budget, chunk=chunk)
            walls.append(time.perf_counter() - t0)
        return statistics.median(walls), fin, eng

    w_off, fin, eng_off = med("off")
    _assert_wave_done(eng_off, fin, n)
    import numpy as np
    delivered = int(np.asarray(jax.device_get(fin.delivered)).sum())
    overheads = {}
    for mode in ("guard", "digest", "shadow"):
        w_mode, fin_m, eng_m = med(mode)
        assert eng_m.last_run_integrity["rollbacks"] == 0, \
            f"verify={mode} false positive on a clean run"
        overheads[mode] = round(w_mode / w_off - 1.0, 4)
    strict = jax.default_backend() == "tpu" and not _SMOKE
    limit = 0.10 if strict else 1.0
    assert overheads["digest"] <= limit, (
        f"verify='digest' costs {overheads['digest']:.1%} — over the "
        f"{limit:.0%} budget (integrity/ overhead contract; chip "
        "re-run owed for the strict bound)")
    return (f"gossip broadcast wave to quiescence (verified chunked "
            f"driver, verify=off) delivered-messages/sec/chip "
            f"@{n} nodes", delivered / w_off,
            {"verify_overhead_frac": overheads})


def bench_gossip_100k_record(n, steps):
    """Causal flight recorder (obs/flight.py, docs/observability.md):
    the gossip wave through the traced chunked driver under every
    record mode, reporting ``record_overhead_frac`` per mode vs the
    same driver with record off. Gated in-bench by the record
    exactness law (off ≡ deliveries ≡ full, bit-for-bit on states
    AND trace rows, before any measured number counts) and by the
    deliveries-mode overhead budget: <= 10% at the SMOKE shape and
    above, CPU included — the slim deliveries row is one cumsum +
    searchsorted compaction per superstep (obs/flight.py
    ``record_deliveries``), cheap enough that even noisy CPU smoke
    windows must clear it. Below the SMOKE shape (the tier-1 tiny
    run) the measured windows are too short for the ratio to mean
    anything, so — like ``_telemetry_gate`` and
    ``gossip_100k_verify`` — the bound loosens to a catastrophic
    2x regression check and the honest ratio rides the JSON line.
    Full mode
    (sends + fault captures across the routing switch) rides the
    JSON line honestly, ungated. Event/drop counts are reported too:
    a nonzero ``dropped`` means the wave peak outran ``record_cap``
    (counted, never silent — obs/flight.py)."""
    import statistics

    import numpy as np

    from timewarp_tpu.interp.jax_engine.engine import JaxEngine
    from timewarp_tpu.trace.events import (assert_states_equal,
                                           assert_traces_equal)

    n = n or 100_000
    sc, link = _gossip_wave(n)
    cap = 4096

    def make(mode):
        return JaxEngine(sc, link, window="auto", lint="off",
                         record=mode, record_cap=cap)

    # the exactness gate: every mode is the same emulation
    off = make("off")
    f_off, tr_off = off.run(24)
    for mode in ("deliveries", "full"):
        eng = make(mode)
        f, tr = eng.run(24)
        assert_traces_equal(tr_off, tr, "record-off",
                            f"record-{mode}")
        assert_states_equal(f_off, f, f"record={mode} exactness gate")

    budget = steps or (1 << 20)
    chunk = 256

    def drive(eng):
        # the chunked traced drive a recorded run actually uses (the
        # whole-budget scan would materialize a [budget, cap] event
        # plane; chunking bounds it at [chunk, cap], drained per
        # chunk like run_stream/run_verified do)
        st = eng.init_state()
        done = events = dropped = 0
        while done < budget:
            step = int(min(chunk, budget - done))
            st, tr = eng.run(step, state=st)
            done += len(tr)
            log = eng.last_run_flight
            if log is not None:
                events += len(log)
                dropped += log.dropped
            if len(tr) < step:      # quiesced inside the chunk
                break
        return st, events, dropped

    def med(mode, reps=3):
        eng = make(mode)
        drive(eng)                  # warm the compiles
        walls, out = [], None
        for _ in range(reps):
            t0 = time.perf_counter()
            out = drive(eng)
            walls.append(time.perf_counter() - t0)
        return statistics.median(walls), out, eng

    w_off, (fin, _, _), eng_off = med("off")
    _assert_wave_done(eng_off, fin, n)
    delivered = int(np.asarray(jax.device_get(fin.delivered)).sum())
    overheads, counts = {}, {}
    for mode in ("deliveries", "full"):
        w_m, (_f, events, dropped), _e = med(mode)
        overheads[mode] = round(w_m / w_off - 1.0, 4)
        counts[mode] = {"events": events, "dropped": dropped}
    strict = n >= SMOKE["gossip_100k_record"][0]
    limit = 0.10 if strict else 1.0
    assert overheads["deliveries"] <= limit, (
        f"record='deliveries' costs {overheads['deliveries']:.1%} on "
        f"the traced chunked driver — over the {limit:.0%} budget "
        "(obs/flight.py overhead contract)")
    return (f"gossip broadcast wave to quiescence (traced chunked "
            f"driver, record=off) delivered-messages/sec/chip "
            f"@{n} nodes", delivered / w_off,
            {"record_overhead_frac": overheads,
             "record_events": counts, "record_cap": cap})


def bench_serve_gossip(n, steps):
    """Emulation as a service (serve/, docs/serving.md): a
    work-stealing curator thread plus an in-process admission book —
    the serving layer WITHOUT the TCP hop, so the number isolates the
    machinery (admission journaling, lease renewal, open-bucket
    engine rebuilds, checkpoints, result streaming) from loopback
    latency; the CI serve-smoke job measures the wire path. Eight
    gossip configs (heterogeneous seeds + budgets, one faulted) are
    submitted against ONE 8-slot open bucket — half up front, half
    mid-bucket while the first chunks run, so admission-into-reserved-
    slots is exercised every round AGAINST A WARM EXECUTABLE: the
    zero-recompile law (identity as traced operands, serve/worker.py)
    is gated in-bench — the journaled ``bucket_util`` must report
    ``engine_builds == 1`` across every mid-bucket admission, and
    both counters ride the JSON line so the ledger can gate
    ``admit_per_s`` against its causal explanation. Reports
    end-to-end served configs/sec (first admit -> last world_done,
    journal ts) plus admission throughput and p50/p95
    submit->world_done latency on the BENCH_SCHEMA=2 line. Gated by
    the extended survival law before the number counts: every
    streamed record's result must be bit-identical to the solo run
    of its config. Runs TWO legs — ``--pack first-fit`` then ``--pack
    predicted`` with a forecaster fitted in-bench from the first
    leg's own results (training_rows -> fit_rows, pack/predict.py) —
    and gates the predicted leg: one journaled ``pack_decision`` per
    admission BEFORE its admit record naming the bucket the admit
    landed in, engine builds unchanged, survival law on both legs.
    Both legs' ``budget_efficiency``/``pad_waste_frac`` rollups ride
    the line for `ledger compare` (the strict packed-vs-first-fit
    win is gated where the plan is deterministic —
    ``bench_sweep_hetero``)."""
    import shutil
    import tempfile
    import threading

    from timewarp_tpu.serve.curator import ServeCurator
    from timewarp_tpu.serve.frontend import ServeFrontend
    from timewarp_tpu.sweep import SweepJournal
    from timewarp_tpu.sweep.spec import RunConfig, solo_result

    n = n or 4096
    steps = steps or 2000
    gossip = {"nodes": n, "fanout": 4, "burst": True,
              "end_us": 400_000, "mailbox_cap": 16, "think_us": 700}
    cfgs = []
    for i in range(8):
        d = {"id": f"w{i}", "scenario": "gossip", "params": gossip,
             "link": "quantize:1000:uniform:3000:9000", "seed": i,
             "budget": steps if i % 2 == 0 else max(steps // 2, 8)}
        if i == 3:
            d["faults"] = "crash:1:5ms:40ms:reset"
        cfgs.append(d)
    from timewarp_tpu.sweep.journal import util_rollup

    def leg(pack_mode, artifact=None):
        root = tempfile.mkdtemp(prefix="tw_serve_bench_")
        try:
            journal = SweepJournal(root, host="bench")
            front = ServeFrontend(journal, "bench", ("127.0.0.1", 0),
                                  slots=8, pack_mode=pack_mode,
                                  pack_artifact=artifact)
            cur = ServeCurator(root, "bench",
                               chunk=max(32, steps // 8),
                               lint="off", lease_ttl_s=60.0,
                               poll_s=0.02, journal=journal,
                               pack_mode=pack_mode,
                               pack_artifact=artifact)
            t0 = time.perf_counter()
            for d in cfgs[:4]:
                front.admit(d)
            admit_half = time.perf_counter()
            worker = threading.Thread(target=cur.run, daemon=True)
            worker.start()
            # mid-bucket admission: the curator is already running
            # the first chunks when these land in the reserved slots
            for d in cfgs[4:]:
                front.admit(d)
            admit_done = time.perf_counter()
            journal.append({"ev": "serve_drain", "host": "bench"})
            worker.join(timeout=600)
            assert not worker.is_alive(), "serve curator never drained"
            dt = time.perf_counter() - t0
            scan = SweepJournal(root).scan()
            assert sorted(scan.done) == sorted(d["id"] for d in cfgs), \
                f"unserved worlds: {sorted(scan.done)}"
            # the extended survival law, world by world, on BOTH legs
            # (the gate deliberately costs a second pass —
            # docs/serving.md): placement policy changes WHERE a world
            # runs, never what it streams
            for d in cfgs:
                cfg = RunConfig.from_json(d, 0)
                want = solo_result(cfg, lint="off")
                got = scan.done[d["id"]]
                assert want == got, (
                    f"serve survival law violated for {d['id']} "
                    f"({pack_mode}):\n"
                    f"  solo:     {want}\n  streamed: {got}")
            # submit->world_done latency per world from the journal's
            # own ts stamps (admit append -> world_done append, one
            # clock)
            t_admit, t_done = {}, {}
            for e in scan.events:
                if e.get("ev") == "admit" \
                        and e["run_id"] not in t_admit:
                    t_admit[e["run_id"]] = float(e["ts"])
                elif e.get("ev") == "world_done":
                    t_done[e["result"]["run_id"]] = float(e["ts"])
            lats = sorted(t_done[r] - t_admit[r] for r in t_done)
            p50 = lats[len(lats) // 2]
            p95 = lats[min(len(lats) - 1, int(len(lats) * 0.95))]
            delivered = sum(r["delivered"]
                            for r in scan.done.values())
            # the zero-recompile serving gate, pinned on BOTH legs: 4
            # of the 8 configs landed mid-bucket (one faulted,
            # fault-pad-compatible with the warmup build), yet each
            # bucket's executable compiled ONCE — admission is an
            # operand write, never a rebuild, whichever bucket the
            # placement policy picked
            builds = {b: u.get("engine_builds")
                      for b, u in scan.util.items()}
            assert builds and all(v == 1 for v in builds.values()), (
                f"mid-bucket admission rebuilt an engine ("
                f"{pack_mode}): {builds} — the zero-recompile "
                "serving law (serve/worker.py rebind_identity)")
            compiles = sum(int(u.get("compiles", 0))
                           for u in scan.util.values())
            return {
                "dt": dt, "scan": scan,
                "roll": util_rollup(scan.util),
                "admit_per_s": round(
                    len(cfgs) / max(1e-9, (admit_half - t0)
                                    + (admit_done - admit_half)), 2),
                "p50": p50, "p95": p95,
                "builds": sum(builds.values()),
                "compiles": compiles, "delivered": delivered,
            }
        finally:
            shutil.rmtree(root, ignore_errors=True)

    ff = leg("first-fit")
    # fit the superstep forecaster from the first leg's own journal —
    # the full training loop (training_rows -> fit_rows) exercised
    # in-bench, exactly what `ledger add` + `pack fit` assemble
    from timewarp_tpu.pack import fit_rows, training_rows
    rows = training_rows(
        [RunConfig.from_json(d, 0) for d in cfgs], ff["scan"].done)
    assert len(rows) == len(cfgs), \
        f"training_rows dropped worlds: {len(rows)}/{len(cfgs)}"
    art = fit_rows(rows)
    pr = leg("predicted", artifact=art)
    # the predictive-placement gate: every admission journaled ONE
    # pack_decision BEFORE its admit record (decision-before-effect),
    # naming the bucket the admit then landed in; first-fit journals
    # nothing (its placement is a pure function of admission order)
    assert not ff["scan"].pack_decisions, \
        "first-fit leg journaled pack_decision records"
    places = {d["run_id"]: d for d in pr["scan"].pack_decisions
              if d.get("kind") == "place"}
    assert sorted(places) == sorted(x["id"] for x in cfgs), (
        f"predicted leg journaled placements for {sorted(places)}, "
        f"admitted {sorted(x['id'] for x in cfgs)}")
    for rid, a in pr["scan"].admits.items():
        if "repacked_from" in a:
            continue
        assert places[rid]["bucket"] == a["bucket"], (
            f"pack_decision for {rid} named bucket "
            f"{places[rid]['bucket']} but the admit landed in "
            f"{a['bucket']} — the journaled decision must BE the "
            "placement")
    # packing rollups on both legs: with one 8-slot bucket the two
    # policies pack identically, so the packed leg must not LOSE
    # anything — the strict packed-vs-first-fit win is gated where
    # the plan is deterministic (bench_sweep_hetero); here the gate
    # pins that predicted placement + its journaling perturb nothing
    assert pr["builds"] == ff["builds"], (
        f"placement policy changed engine build count: "
        f"{pr['builds']} predicted vs {ff['builds']} first-fit")
    extra = {
        "worlds": len(cfgs),
        "admit_per_s": pr["admit_per_s"],
        "submit_p50_s": round(pr["p50"], 4),
        "submit_p95_s": round(pr["p95"], 4),
        "buckets": len(pr["scan"].serve_buckets),
        "engine_builds": pr["builds"],
        "compiles": pr["compiles"],
        "delivered_per_s": round(pr["delivered"] / pr["dt"], 2),
        # the packing rollups (sweep/journal.py util_rollup) —
        # promoted to the ledger index so `ledger compare` rate-gates
        # packing regressions across rounds
        "budget_efficiency": pr["roll"]["budget_efficiency"],
        "pad_waste_frac": pr["roll"]["pad_waste_frac"],
        "first_fit_budget_efficiency":
            ff["roll"]["budget_efficiency"],
        "first_fit_pad_waste_frac": ff["roll"]["pad_waste_frac"],
        "pack_decisions": len(pr["scan"].pack_decisions),
        "predictor_sha": art["sha"][:12],
    }
    return (f"emulation service (admission + open buckets + stream + "
            f"survival law + predictive placement) served "
            f"configs/sec @{n} nodes", len(cfgs) / pr["dt"], extra)


def bench_lint_sweep(n, steps):
    """Fleet-scale static verification (analysis/, docs/sweeps.md +
    docs/serving.md "Pre-flight verification"): time the three pass
    families a fleet pays BEFORE any engine builds — the scenario
    sanitizer sweep over every shipped model (the same sweep as this
    bench's own pre-run gate), the plan lint over every example pack
    (bucket/width/window prediction, fault-pad rebuild detection,
    fault-aware capacity proofs), and the jaxpr determinism sweep
    over every shipped engine x observability mode (TW7xx scans plus
    the TW705 off-mode neutrality proofs). Gated in-bench both ways:
    the shipped models, the clean example packs, and the jaxpr sweep
    must lint ZERO errors, and the doomed example pack must FAIL —
    the refusal corpus staying refused is as much a contract as the
    clean corpus staying clean. Reports verified subjects+configs/sec
    with per-surface second splits on the BENCH_SCHEMA=2 line: the
    honest price of refuse-before-run at sweep-prepare/admission
    time."""
    import glob as globlib

    from timewarp_tpu.analysis import lint_pack_path
    from timewarp_tpu.cli import jaxpr_sweep, lint_sweep

    n = n or 64
    here = os.path.dirname(os.path.abspath(__file__))
    packs = sorted(globlib.glob(
        os.path.join(here, "examples", "packs", "*.json")))
    assert packs, "examples/packs/*.json missing"
    t0 = time.perf_counter()
    subjects, rep = lint_sweep(nodes=n)
    assert rep.ok, f"shipped models failed lint:\n{rep.render()}"
    t1 = time.perf_counter()
    configs = 0
    for path in packs:
        n_entries, prep = lint_pack_path(path)
        configs += n_entries
        if os.path.basename(path).startswith("doomed"):
            assert not prep.ok, (
                f"{path}: the doomed refusal corpus linted GREEN — "
                "the refuse-before-run gate has gone blind")
        else:
            assert prep.ok, (
                f"{path}: shipped example pack failed the plan "
                f"lint:\n{prep.render()}")
    t2 = time.perf_counter()
    # abstract tracing: the driver's primitive inventory does not
    # change with fleet width, so the jaxpr sweep stays at 8 nodes
    jx_subjects, jx_rep = jaxpr_sweep(nodes=8)
    assert jx_rep.ok, (
        f"jaxpr determinism sweep failed:\n{jx_rep.render()}")
    assert any(f.code == "TW705" for f in jx_rep.infos), \
        "no TW705 neutrality proofs in the jaxpr sweep"
    t3 = time.perf_counter()
    total = subjects + configs + jx_subjects
    extra = {
        "lint_subjects": subjects,
        "pack_files": len(packs),
        "pack_configs": configs,
        "jaxpr_subjects": jx_subjects,
        "sanitizer_s": round(t1 - t0, 2),
        "plan_s": round(t2 - t1, 2),
        "jaxpr_s": round(t3 - t2, 2),
    }
    return (f"static pre-flight verification (sanitizer + plan lint "
            f"+ jaxpr determinism sweep, refusal corpus gated) "
            f"verified subjects/sec @{n} nodes",
            total / (t3 - t0), extra)


CONFIGS = {
    "token_ring_dense": bench_token_ring_dense,
    "token_ring_dense_xla": bench_token_ring_dense_xla,
    "token_ring_observer": bench_token_ring_observer,
    "gossip_100k": bench_gossip_100k,
    "gossip_100k_fused": bench_gossip_100k_fused,
    "gossip_100k_insert": bench_gossip_100k_insert,
    "gossip_100k_b8": bench_gossip_100k_b8,
    "gossip_100k_chaos": bench_gossip_100k_chaos,
    "gossip_100k_auto": bench_gossip_100k_auto,
    "gossip_100k_spec": bench_gossip_100k_spec,
    "gossip_100k_verify": bench_gossip_100k_verify,
    "gossip_100k_record": bench_gossip_100k_record,
    "gossip_steady_1m": bench_gossip_steady_1m,
    "praos_1m": bench_praos_1m,
    "praos_1m_fused": bench_praos_1m_fused,
    "praos_1m_insert": bench_praos_1m_insert,
    "praos_1m_b4": bench_praos_1m_b4,
    "sweep_hetero": bench_sweep_hetero,
    "sweep_hetero_auto": bench_sweep_hetero_auto,
    "search_gossip": bench_search_gossip,
    "serve_gossip": bench_serve_gossip,
    "lint_sweep": bench_lint_sweep,
}

#: --smoke shapes: every config tiny enough for a CPU CI runner, all
#: in-bench exactness gates live (the fused ring's 8192-node floor
#: pins that row's size; the fused-sparse rows gate at 2048)
SMOKE = {
    "token_ring_dense": (8192, 16),
    "token_ring_dense_xla": (4096, 32),
    "token_ring_observer": (1024, 32),
    "gossip_100k": (2048, 1 << 14),
    "gossip_100k_fused": (2048, 1 << 14),
    "gossip_100k_insert": (2048, 1 << 14),
    "gossip_100k_b8": (1024, 1 << 14),
    "gossip_100k_chaos": (1024, 1 << 14),
    "gossip_100k_auto": (1024, 1 << 14),
    "gossip_100k_spec": (1024, 1 << 14),
    "gossip_100k_verify": (1024, 1 << 14),
    "gossip_100k_record": (1024, 1 << 14),
    "gossip_steady_1m": (4096, 16),
    "praos_1m": (2048, 24),
    "praos_1m_fused": (2048, 24),
    "praos_1m_insert": (2048, 24),
    "praos_1m_b4": (1024, 24),
    "sweep_hetero": (256, 96),
    "sweep_hetero_auto": (256, 96),
    "search_gossip": (64, 300),
    "serve_gossip": (256, 96),
    "lint_sweep": (64, 1),
}


def _calibrate():
    """Session-condition fingerprint: a frozen XLA kernel (64 rounds of
    ``lax.sort`` over 2^20 int32 — the op profile that dominates the
    general engine) whose code must NEVER change across rounds.
    Comparing the ``calib`` field across ``BENCH_r*.json`` separates
    chip/tunnel variance (±20% session-to-session, PERF_r03.md) from
    actual framework changes — the self-calibration VERDICT r3 asked
    the artifact to carry."""
    import jax.numpy as jnp
    from jax import lax

    @jax.jit
    def kern(x):
        def body(i, x):
            return lax.sort(x * jnp.int32(1103515245) + i)
        return lax.fori_loop(jnp.int32(0), jnp.int32(64), body, x)

    x = jnp.arange(1 << 20, dtype=jnp.int32)
    int(kern(x)[0])  # compile; readback = sync (block_until_ready is
    t0 = time.perf_counter()  # NOT a true sync on the tunnel backend)
    int(kern(x)[0])
    dt = time.perf_counter() - t0
    return {"kernel": "sort_1m_int32_x64", "seconds": round(dt, 4)}


def _lint_gate() -> None:
    """Scenario sanitizer sweep (timewarp_tpu.analysis) over every
    shipped model and program twin before any config runs: a bench
    number for a contract-violating scenario is a number about
    nothing. Same sweep as CI's `lint` job — but silent on success,
    so the bench contract (one JSON line per config/run on stdout)
    holds."""
    from timewarp_tpu.cli import lint_sweep
    _, report = lint_sweep()
    if not report.ok:
        sys.stderr.write(report.render() + "\n")
        raise SystemExit(
            "bench: error-severity lint findings in shipped models "
            "(run `timewarp-tpu lint` for the report)")


def smoke() -> None:
    """CI fast path: every config at its SMOKE shape, exactness gates
    on, one JSON line each. Throughput numbers at smoke scale are
    meaningless and marked so — the value of this mode is that a
    kernel-vs-engine divergence or a broken parity-regime invariant
    raises before a full bench round ever runs. TW_BENCH_CONFIG (a
    comma-separated subset) restricts the sweep — the regression-gate
    CI job runs a cheap two-config smoke twice into a ledger rather
    than paying for the full sweep twice."""
    _lint_gate()
    env = _env_fields()
    cfgs = SMOKE
    only = os.environ.get("TW_BENCH_CONFIG")
    if only:
        names = [s.strip() for s in only.split(",") if s.strip()]
        unknown = sorted(set(names) - set(SMOKE))
        if unknown:
            raise SystemExit(
                f"TW_BENCH_CONFIG names unknown configs {unknown}; "
                f"choose from {sorted(SMOKE)}")
        cfgs = {k: SMOKE[k] for k in names}
    for cfg, (n, steps) in cfgs.items():
        t0 = time.perf_counter()
        metric, _rate, extra = _run_config(cfg, n, steps)
        _emit({
            "config": cfg, "config_key": _config_key(cfg, n, steps),
            "metric": metric, "smoke": True,
            "ok": True, "seconds": round(time.perf_counter() - t0, 1),
            **env, **extra,
        })


def _run_config(cfg, n, steps):
    """Run one config; normalize its return to (metric, rate, extra).
    ``extra`` is a dict of additional JSON-line fields (the chaos
    config reports per-world route_drop / fault_dropped — the
    never-silent contract on the world axis)."""
    res = CONFIGS[cfg](n, steps)
    metric, rate = res[0], res[1]
    extra = res[2] if len(res) > 2 else {}
    return metric, rate, extra


def _parse_ledger() -> None:
    """--ledger DIR: auto-append every emitted line to the cross-run
    ledger (obs/ledger.py) under one fresh batch label per
    invocation, so `timewarp-tpu ledger compare` can gate this run
    against any earlier one."""
    if "--ledger" not in sys.argv:
        return
    try:
        d = sys.argv[sys.argv.index("--ledger") + 1]
    except IndexError:
        raise SystemExit("--ledger takes a ledger directory")
    if d.startswith("--"):
        raise SystemExit(f"--ledger takes a ledger directory, "
                         f"got {d!r}")
    from timewarp_tpu.obs.ledger import RunLedger
    global _LEDGER
    led = RunLedger(d)
    _LEDGER = (led, led.new_batch())


def main() -> None:
    _parse_ledger()
    if "--smoke" in sys.argv:
        if "--reps" in sys.argv:
            # never-silent knob convention: smoke's value is its gates,
            # not its (meaningless-at-smoke-scale) rates — a dropped
            # rep count must not masquerade as a median-of-K number
            raise SystemExit("--reps applies to measured runs only; "
                             "--smoke rates are not measurements")
        global _SMOKE
        _SMOKE = True
        smoke()
        return
    _lint_gate()
    reps = 1
    if "--reps" in sys.argv:
        # median-of-K measurement: whole-run rates swing ±12% through
        # the tunnel (PERF_r05.md), so a single rep cannot honestly
        # rank batched vs solo — report the median with the spread
        try:
            reps = int(sys.argv[sys.argv.index("--reps") + 1])
        except (IndexError, ValueError):
            raise SystemExit("--reps takes an integer rep count K")
        if reps < 1:
            raise SystemExit(f"--reps must be >= 1, got {reps}")
    cfg = os.environ.get("TW_BENCH_CONFIG", "token_ring_dense")
    n = int(os.environ.get("TW_BENCH_NODES", 0)) or None
    steps = int(os.environ.get("TW_BENCH_STEPS", 0)) or None
    global _REPS
    _REPS = reps  # _measure repeats the window; gates/compiles run once
    metric, rate, extra = _run_config(cfg, n, steps)
    out = {
        "config": cfg,
        "config_key": _config_key(cfg, n, steps),
        "metric": metric,
        "value": round(rate, 1),  # the median-of-K rate (K = --reps)
        "unit": "msg/s",
        "vs_baseline": round(rate / 1e8, 4),
        **_env_fields(),
        **extra,
    }
    if reps > 1:
        out["reps"] = reps
        out["min"] = round(_SPREAD["min"], 1)
        out["max"] = round(_SPREAD["max"], 1)
    out["calib"] = _calibrate()
    _emit(out)


if __name__ == "__main__":
    main()
