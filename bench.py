"""Headline benchmark: delivered-messages/sec/chip on the dense token ring.

The flagship workload is the reference's north-star scenario
(`/root/reference/examples/token-ring/Main.hs`) generalized to a dense
ring — every node holds a token, so each superstep fires all N nodes and
delivers N messages — at the BASELINE.json target scale (1M simulated
nodes, delivered-messages/sec/chip, target >= 1e8).

Runs on the edge engine (interp/jax_engine/edge_engine.py): the ring's
static topology makes delivery a fused neighbor shift — no sort, no
scatter (profiling/superstep_breakdown.md).

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.
``vs_baseline`` is value / 1e8 (the north-star target; the reference
itself publishes no numbers — BASELINE.md).

Env knobs: TW_BENCH_NODES (default 1048576), TW_BENCH_STEPS (default 256).
"""

import json
import os
import time

from timewarp_tpu.utils import jaxconfig  # noqa: F401

import jax

from timewarp_tpu.interp.jax_engine.edge_engine import EdgeEngine
from timewarp_tpu.models.token_ring import token_ring
from timewarp_tpu.net.delays import FixedDelay


def main() -> None:
    n = int(os.environ.get("TW_BENCH_NODES", 1 << 20))
    steps = int(os.environ.get("TW_BENCH_STEPS", 256))

    # Dense ring, think_us=0: a node receiving a token forwards it in
    # the same firing, so every superstep delivers exactly N messages.
    # end_us far enough that the deadline never quiesces the run.
    sc = token_ring(
        n, n_tokens=n, think_us=0, bootstrap_us=1_000,
        end_us=(1 << 50), with_observer=False, mailbox_cap=4)
    engine = EdgeEngine(sc, FixedDelay(500), cap=2)

    st = engine.init_state()
    st = jax.block_until_ready(st)

    # Warmup: compile the while_loop driver (first TPU compile 20-40 s).
    warm = engine.run_quiet(2, st)
    int(warm.delivered)  # force completion via host readback

    t0 = time.perf_counter()
    fin = engine.run_quiet(steps, warm)
    delivered = int(fin.delivered) - int(warm.delivered)  # forces readback
    dt = time.perf_counter() - t0

    rate = delivered / dt
    print(json.dumps({
        "metric": f"token-ring dense delivered-messages/sec/chip @{n} nodes",
        "value": round(rate, 1),
        "unit": "msg/s",
        "vs_baseline": round(rate / 1e8, 4),
    }))


if __name__ == "__main__":
    main()
